"""Asynchronous (hogwild) training against the parameter server.

Reference: ``sparktorch/hogwild.py`` — HTTP client helpers with one
retry (:31-62), a per-partition worker loop that pulls the full
state_dict, does forward/backward, pushes raw grads and polls early
stop (:65-142), and a driver ``train()`` that runs partition-shuffle
rounds and pulls final weights (:145-186).

TPU-native redesign:

- Workers are device-pinned: each worker owns a chip, holds its data
  shard in that chip's HBM, and runs one jitted gradient step per
  iteration. Pulls are version-tagged (no redundant transfers), and
  the push is the local weighted-mean gradient pytree.
- The reference's missing ``zero_grad`` (grads accumulate across
  iterations, ``hogwild.py:96-140`` — SURVEY flags it as a real
  behavioral quirk) is deliberately NOT reproduced: each push is the
  gradient of the current minibatch only.
- Transports: ``local`` (in-process, device-to-device) or ``http``.
  The HTTP wire defaults to the framed zero-copy binary protocol
  (:mod:`sparktorch_tpu.net`): persistent keep-alive connections,
  ``np.frombuffer`` decode, 304 not-modified pulls, quantized pushes
  with error feedback. ``wire='dill'`` falls back to the reference's
  wire shape (dill blobs, stdlib client with one retry + timeout like
  ``hogwild.py:34-38``) for parity runs and mixed-version gangs.
"""

from __future__ import annotations

import threading
import time
import urllib.error
import urllib.request
from typing import Any, List, Optional

import dill
import jax
import jax.numpy as jnp
import numpy as np

from sparktorch_tpu.ft import chaos as _chaos
from sparktorch_tpu.obs import goodput as _goodput
from sparktorch_tpu.obs import health as _health
from sparktorch_tpu.net.transport import BinaryTransport
from sparktorch_tpu.obs import get_logger, get_telemetry
from sparktorch_tpu.serve.param_server import ParameterServer, ParamServerHttp
from sparktorch_tpu.train.step import _sown_total
from sparktorch_tpu.train.sync import TrainResult, _as_batch
from sparktorch_tpu.utils.data import DataBatch
from sparktorch_tpu.utils.serde import deserialize_model
from sparktorch_tpu.utils.tracing import profile_run, step_annotation

_HTTP_TIMEOUT = 10.0  # hogwild.py:34-38 parity (10s timeout, 1 retry)
# Pulls carry the full model snapshot; on a tunnel-attached chip the
# server's first host materialization of a new version takes seconds —
# and the rig's wire oscillates down to <1 MB/s in troughs — so the
# pull deadline is its own, generous one (the push/poll paths keep
# reference parity).
_HTTP_PULL_TIMEOUT = 180.0


# ---------------------------------------------------------------------------
# Transports
# ---------------------------------------------------------------------------


def _new_phase_stats() -> dict:
    """Per-transport phase accounting (seconds, bytes, counts) — the
    raw material for the hogwild budget the bench publishes: where a
    worker's wall time actually goes (pull wire, push materialize+wire,
    stop-poll), so ``async_efficiency`` decomposes instead of being one
    unexplained ratio."""
    return {
        "pull_s": 0.0, "pull_bytes": 0, "pulls": 0, "pull_fresh": 0,
        "push_wire_s": 0.0, "push_materialize_s": 0.0,
        "push_bytes": 0, "pushes": 0,
        "poll_s": 0.0,
    }


class LocalTransport:
    """Direct in-process access to the server object."""

    def __init__(self, server: ParameterServer):
        self.server = server
        self.stats = _new_phase_stats()

    def pull(self, have_version: int):
        t0 = time.perf_counter()  # lint-obs: ok (phase stats; the worker loop feeds the ledger from these)
        snap = self.server.get_parameters(have_version)
        st = self.stats
        st["pull_s"] += time.perf_counter() - t0  # lint-obs: ok (phase stats pair)
        st["pulls"] += 1
        st["pull_fresh"] += snap is not None
        return snap

    def push(self, grads) -> None:
        t0 = time.perf_counter()  # lint-obs: ok (phase stats pair)
        self.server.push_gradients(grads)
        self.stats["push_wire_s"] += time.perf_counter() - t0  # lint-obs: ok (phase stats pair)
        self.stats["pushes"] += 1

    def post_loss(self, loss: float) -> bool:
        t0 = time.perf_counter()  # lint-obs: ok (phase stats pair)
        out = self.server.post_loss(loss)
        self.stats["poll_s"] += time.perf_counter() - t0  # lint-obs: ok (phase stats pair)
        return out

    def alive(self) -> bool:
        return True


class HttpTransport:
    """The reference's wire (hogwild.py:31-62): dill over HTTP with
    one retry and a 10s timeout per call.

    Unlike the reference — which ships full-precision state both ways
    every iteration (its 2x-model-per-iter pathology) — pushes are
    bf16-compressed by default: gradients tolerate the 8-bit mantissa
    (it is the TPU's native matmul dtype) and the wire bytes halve.
    The server casts back up to the param dtype before the optimizer
    update, so moments stay full precision."""

    def __init__(self, url: str, compress: bool = True):
        self.url = url.rstrip("/")
        self.compress = compress
        self.stats = _new_phase_stats()

    def _request(self, req, timeout: float = _HTTP_TIMEOUT,
                 retry_on_timeout: bool = False):
        """One retry, reference parity. Timeouts retry only when the
        caller says the request is IDEMPOTENT (the pull GET): a timed-
        out POST may still complete server-side, and re-sending it
        would double-apply a gradient or double-count a loss."""
        retriable = (urllib.error.URLError, ConnectionError)
        if retry_on_timeout:
            retriable = retriable + (TimeoutError,)
        try:
            return urllib.request.urlopen(  # lint-obs: ok (dill data wire)
                req, timeout=timeout)
        except retriable:
            return urllib.request.urlopen(  # lint-obs: ok (dill data wire)
                req, timeout=timeout)  # retry once

    def pull(self, have_version: int):
        st = self.stats
        t0 = time.perf_counter()  # lint-obs: ok (phase stats pair)
        req = urllib.request.Request(
            self.url + "/parameters", headers={"X-Have-Version": str(have_version)}
        )
        with self._request(req, timeout=_HTTP_PULL_TIMEOUT,
                           retry_on_timeout=True) as resp:
            if resp.status == 204:
                st["pull_s"] += time.perf_counter() - t0  # lint-obs: ok (phase stats pair)
                st["pulls"] += 1
                return None
            body = resp.read()
        st["pull_s"] += time.perf_counter() - t0  # lint-obs: ok (phase stats pair)
        st["pulls"] += 1
        st["pull_fresh"] += 1
        st["pull_bytes"] += len(body)
        return dill.loads(body)

    def push(self, grads) -> None:
        st = self.stats
        # Materialize separately from the wire: np.asarray FENCES the
        # device (the gradient compute drains here), so this term is
        # the honest compute+download+serialize time and the urlopen
        # below is the pure wire+server-apply time.
        t0 = time.perf_counter()  # lint-obs: ok (phase stats pair)
        if self.compress:
            host_grads = jax.tree.map(
                lambda a: np.asarray(
                    a.astype(jnp.bfloat16)
                    if jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating)
                    else a
                ),
                grads,
            )
        else:
            host_grads = jax.tree.map(lambda a: np.asarray(a), grads)
        # Serialization counts as materialize, not wire — the same
        # bucketing as BinaryTransport (which encodes before ITS t1),
        # so the hogwild_wire bench compares like with like.
        payload = dill.dumps(host_grads)
        t1 = time.perf_counter()  # lint-obs: ok (phase stats pair)
        st["push_materialize_s"] += t1 - t0
        req = urllib.request.Request(
            self.url + "/update", data=payload, method="POST"
        )
        with self._request(req) as resp:
            if resp.status != 200:
                raise RuntimeError(f"/update failed: {resp.status}")
        st["push_wire_s"] += time.perf_counter() - t1  # lint-obs: ok (phase stats pair)
        st["push_bytes"] += len(payload)
        st["pushes"] += 1

    def post_loss(self, loss: float) -> bool:
        t0 = time.perf_counter()  # lint-obs: ok (phase stats pair)
        req = urllib.request.Request(
            self.url + "/losses", data=dill.dumps(float(loss)), method="POST"
        )
        with self._request(req) as resp:
            out = bool(dill.loads(resp.read())["stop"])
        self.stats["poll_s"] += time.perf_counter() - t0  # lint-obs: ok (phase stats pair)
        return out

    def alive(self) -> bool:
        # GET / liveness probe (hogwild.py:60-62).
        req = urllib.request.Request(self.url + "/")
        with self._request(req) as resp:
            return resp.status == 200


# ---------------------------------------------------------------------------
# Worker
# ---------------------------------------------------------------------------


def make_grad_step(apply_fn, loss_fn, mini_batch: Optional[int] = None):
    """Jitted local gradient step: weighted-mean grads + loss of one
    minibatch — the worker half of ``hogwild.handle_model``'s hot loop
    (hogwild.py:96-130), with zero_grad semantics done right.

    With ``mini_batch`` set, the minibatch is sampled ON-DEVICE inside
    the compiled step (random-offset contiguous block — see
    ``utils.data.sample_minibatch`` for why gathers are wrong here):
    the whole iteration is ONE dispatch, vs host-side fancy-indexing
    which costs three device round-trips per iteration before the
    gradient even starts — the dominant cost on anything but a local
    chip."""

    @jax.jit
    def grad_step(params, model_state, shard: DataBatch, key):
        if mini_batch and 0 < mini_batch < shard.x.shape[0]:
            from sparktorch_tpu.utils.data import sample_minibatch

            batch = sample_minibatch(shard, key, mini_batch)
        else:
            batch = shard

        def weighted(params):
            from sparktorch_tpu.train.step import _accepts_example_w

            variables = {"params": params, **(model_state or {})}
            kwargs = (
                {"example_w": batch.w} if _accepts_example_w(apply_fn) else {}
            )
            # Request the write-only 'losses' collection so sown aux
            # objectives (MoE load-balance) train here too — the async
            # router must optimize the same objective as the sync one.
            preds, sown_state = apply_fn(variables, batch.x,
                                         mutable=["losses", "moe_metrics"],
                                         **kwargs)
            per = loss_fn(preds, batch.y)
            num = jnp.sum(per * batch.w)
            den = jnp.maximum(jnp.sum(batch.w), 1.0)
            sown = dict(sown_state).get("losses", None)
            return num / den + _sown_total(sown, per.dtype)

        loss, grads = jax.value_and_grad(weighted)(params)
        return grads, loss

    return grad_step


def make_grad_window(apply_fn, loss_fn, mini_batch: Optional[int], k: int):
    """``k`` minibatch gradient steps fused into ONE compiled call
    (``lax.scan``): returns the mean gradient over the window and the
    k per-step losses. This is the ``push_every`` hot path — a whole
    accumulation window costs a single dispatch, zero per-step Python.
    All k steps see the params the worker last pulled (the window is
    the staleness unit; that's the documented push_every tradeoff)."""

    grad_step = make_grad_step(apply_fn, loss_fn, mini_batch)

    @jax.jit
    def grad_window(params, model_state, shard: DataBatch, key):
        def body(acc, subkey):
            grads, loss = grad_step(params, model_state, shard, subkey)
            acc = jax.tree.map(jnp.add, acc, grads)
            return acc, loss

        zero = jax.tree.map(jnp.zeros_like, params)
        acc, losses = jax.lax.scan(body, zero, jax.random.split(key, k))
        return jax.tree.map(lambda g: g / k, acc), losses

    return grad_window


def make_grad_windows(apply_fn, loss_fn, mini_batch: Optional[int],
                      push_every: int, iters: int):
    """Build the ``(full_window, tail_window)`` pair ``_worker_loop``
    expects for ``push_every=k``: the full k-step window plus a
    remainder window when ``iters % k != 0`` (the full window is reused
    when the division is exact). One source of truth for the tail-
    window contract, shared by ``train_async`` and the Spark executor
    deployment. Returns None when ``push_every <= 1``."""
    if not push_every or push_every <= 1:
        return None
    rem = iters % push_every
    window = make_grad_window(apply_fn, loss_fn, mini_batch, push_every)
    return (
        window,
        make_grad_window(apply_fn, loss_fn, mini_batch, rem) if rem else window,
    )


def make_eval_loss(apply_fn, loss_fn):
    """Jitted full-shard weighted loss (no grads) — the validation
    probe for early stopping."""

    @jax.jit
    def eval_loss(params, model_state, batch: DataBatch):
        variables = {"params": params, **(model_state or {})}
        preds = apply_fn(variables, batch.x)
        per = loss_fn(preds, batch.y)
        return jnp.sum(per * batch.w) / jnp.maximum(jnp.sum(batch.w), 1.0)

    return eval_loss


def _worker_loop(
    worker_id: int,
    device: jax.Device,
    transport,
    grad_step,
    model_state,
    shard: DataBatch,
    val_shard: Optional[DataBatch],
    iters: int,
    verbose: int,
    early_stop: bool,
    seed: int,
    records: List[dict],
    errors: List[BaseException],
    push_every: int = 1,
    eval_loss=None,
    grad_windows=None,
    phase_out: Optional[List[dict]] = None,
    telemetry=None,
    cancel=None,
):
    """One worker's training loop.

    ``push_every<=1``: pull → one jitted grad step (minibatch sampled
    on-device) → push, per iteration. ``push_every=k`` with
    ``grad_windows=(window_k, window_rem)``: a whole k-step
    accumulation window runs as ONE compiled call and pushes its mean
    gradient — k-fold fewer pulls/pushes/dispatches; the window is the
    staleness unit. Losses stay on-device until the loop ends (or
    verbose/early-stop demands a value NOW): a ``float()`` per
    iteration serializes the pipeline on a host round-trip that costs
    more than the gradient step itself on remote-attached chips.

    ``cancel`` (a ``threading.Event``, wired by the supervised path)
    is polled BETWEEN windows: a supervisor ``kill()`` — straggler or
    stall preemption — stops the worker at the next window boundary
    with :class:`WorkerPreempted` instead of being silently ignored
    (threads cannot be preempted mid-dispatch; the window is the
    preemption unit, like it is the staleness unit). A preempted
    attempt flushes no records, so the restarted attempt's rerun
    keeps counts exact.
    """
    tele = telemetry or get_telemetry()
    log = get_logger("sparktorch_tpu.train.hogwild")
    labels = {"worker": worker_id}
    # Per-WORKER health ledger on the shared bus: each worker's loss
    # series and anomalies stay tagged with its own rank ("w<id>") in
    # the composite health section — a NaN on one worker must surface
    # as that worker's NaN, never fleet-averaged. Device losses are
    # queued un-synced; the K-late drain materializes windows whose
    # compute long finished, preserving the async dispatch pipeline.
    hl = (_health.TrainHealthLedger(rank=f"w{worker_id}", telemetry=tele)
          if _health.enabled() else None)
    try:
        if hasattr(transport, "stats"):
            # Fresh per-round stats: the transport object survives
            # shuffle rounds, the budget must not double-count.
            transport.stats = _new_phase_stats()
        shard = jax.device_put(shard, device)
        key = jax.device_put(jax.random.key(seed + worker_id), device)
        have_version = -1
        params = None
        pending: List[Any] = []
        window_k = push_every if push_every and push_every > 1 else 1
        it = 0
        t_place = 0.0   # host->device upload of pulled params
        t_dispatch = 0.0  # grad window dispatch (async; drain lands
        # in the push's materialize fence)
        t_loop0 = time.perf_counter()  # lint-obs: ok (loop-wall clock for the phase budget)
        while it < iters:
            if cancel is not None and cancel.is_set():
                from sparktorch_tpu.ft.supervisor import WorkerPreempted

                raise WorkerPreempted(
                    f"worker {worker_id} preempted at iter {it}"
                )
            # Chaos injection point: a seeded config can kill THIS
            # worker at step N (ChaosKill lands in `errors` like any
            # real failure; under supervision it triggers a restart).
            _chaos.fire("worker.step", worker=worker_id, step=it)
            _act = _chaos.fire("data.batch", worker=worker_id, step=it)
            if _act and _act.get("poison"):
                shard = _chaos.poison_batch(shard)
            # Straggler injection before the pull (this loop's wire
            # fence) and the step span: the skew referee sees a late
            # arrival on this worker.
            _chaos.straggle(worker_id, it)
            # Wire waits are EXPOSED comm by definition (nothing
            # overlaps them in this loop); the pulled params' host->
            # device upload is a data wait. Both ride LedgerSpans so
            # the goodput ledger and the phase budget read one clock.
            with _goodput.span("exposed_comm",
                               {"site": "hogwild_pull"}):
                snap = transport.pull(have_version)
            if snap is not None:
                have_version, params = snap
                with _goodput.span("data_wait",
                                   {"site": "hogwild_place"}) as _pl:
                    params = jax.device_put(params, device)
                t_place += _pl.duration_s

            key, sub = jax.random.split(key)
            k = min(window_k, iters - it)
            # The window dispatch is ASYNC by design (the device
            # compute drains at the push's materialize fence and the
            # end-of-loop drain): the step span here counts steps and
            # catches the dispatch wall; the real device seconds land
            # in compute via the materialize/drain attributions below.
            with _goodput.step_span(step=it) as _led:
                with step_annotation(it, telemetry=tele):
                    if window_k > 1 and grad_windows is not None:
                        fn = (grad_windows[0] if k == window_k
                              else grad_windows[1])
                        grads, losses = fn(params, model_state, shard, sub)
                    else:
                        k = 1
                        grads, losses = grad_step(params, model_state,
                                                  shard, sub)
                _led.count = k
            t_dispatch += _led.duration_s
            _pre = (dict(getattr(transport, "stats", None) or {})
                    if _goodput.active() is not None else None)
            transport.push(grads)
            _post = (getattr(transport, "stats", None)
                     if _pre is not None else None)
            if _post is not None:
                # Split the push by the transport's own phase stats:
                # the materialize half FENCES the device (that is the
                # window's gradient compute draining — productive),
                # the wire half is exposed comm.
                _goodput.add("compute",
                             _post["push_materialize_s"]
                             - (_pre or {}).get("push_materialize_s", 0.0))
                _goodput.add("exposed_comm",
                             _post["push_wire_s"]
                             - (_pre or {}).get("push_wire_s", 0.0))
            tele.counter("hogwild.iters", k, labels=labels)
            tele.counter("hogwild.pushes", labels=labels)
            tele.gauge("hogwild.pulled_version", have_version, labels=labels)
            pending.append((it, k, have_version, losses, time.perf_counter()))  # lint-obs: ok (throughput timestamp)
            if hl is not None:
                hl.note_step(step=it, count=k, device={"loss": losses})
            it += k
            if verbose:
                last = jnp.reshape(jnp.asarray(losses), (-1,))[-1]
                log.info(f"[sparktorch_tpu:hogwild] worker {worker_id} "
                         f"iter {it - 1} loss {float(last):.6f} v{have_version}")
            if early_stop:
                if eval_loss is not None and val_shard is not None:
                    signal = float(eval_loss(params, model_state, val_shard))
                else:
                    signal = float(
                        jnp.reshape(jnp.asarray(losses), (-1,))[-1]
                    )
                if transport.post_loss(signal):
                    break
        t_drain0 = time.perf_counter()  # lint-obs: ok (phase stats pair, ledger-fed below)
        done = []
        for start, k, version, losses, ts in pending:
            vals = np.asarray(losses).reshape(-1)
            for j in range(k):
                done.append(
                    {"worker": worker_id, "iter": start + j,
                     "loss": float(vals[j]), "version": version, "t": ts}
                )
        if done:
            # Wall time at which this worker's last loss actually
            # materialized (a device sync, unlike the per-window
            # dispatch timestamps) — the honest end of the window for
            # throughput math.
            done[-1]["t_done"] = time.perf_counter()  # lint-obs: ok (throughput timestamp)
        records.extend(done)
        # The drain is where the async windows' device compute lands.
        _goodput.add("compute", time.perf_counter() - t_drain0)  # lint-obs: ok (phase stats pair, feeds the ledger)
        if hl is not None:
            hl.flush()
        if phase_out is not None:
            st = dict(getattr(transport, "stats", {}) or {})
            st.update({
                "worker": worker_id,
                "pull_place_s": t_place,
                "dispatch_s": t_dispatch,
                # The post-loop loss materialization: where the async
                # window dispatches' device compute + link latency
                # actually drains (dominant with the local transport —
                # this IS the per-window-dispatch design cost).
                "drain_s": time.perf_counter() - t_drain0,  # lint-obs: ok (phase stats pair)
                "loop_s": time.perf_counter() - t_loop0,  # lint-obs: ok (phase stats pair)
                "iters": it,
            })
            phase_out.append(st)
            # Mirror the per-round phase budget onto the bus so the
            # same decomposition shows up in /metrics and JSONL dumps
            # alongside the counters bumped in the loop above.
            for phase in ("pull_s", "pull_place_s", "dispatch_s",
                          "push_materialize_s", "push_wire_s", "poll_s",
                          "drain_s", "loop_s"):
                if st.get(phase):
                    tele.observe(f"hogwild.{phase}", float(st[phase]),
                                 labels=labels)
    except BaseException as e:  # surfaced to the driver
        errors.append(e)


# ---------------------------------------------------------------------------
# Driver
# ---------------------------------------------------------------------------


def train_async(
    torch_obj,
    data: Any,
    labels: Optional[np.ndarray] = None,
    mesh=None,  # accepted for API symmetry; workers pin devices directly
    iters: int = 10,
    partition_shuffles: int = 1,
    verbose: int = 0,
    mini_batch: Optional[int] = None,
    validation_pct: float = 0.0,
    early_stop_patience: int = -1,
    acquire_lock: bool = True,
    port: int = 0,
    partitions: int = -1,
    seed: int = 0,
    transport: str = "local",
    push_every: int = 1,
    compress: bool = True,
    wire: str = "binary",
    quant: Optional[str] = None,
    shards: int = 1,
    pull_quant: Optional[str] = None,
    telemetry=None,
    profile_dir: Optional[str] = None,
    supervise: bool = False,
    ft_policy=None,
) -> TrainResult:
    """Asynchronous parameter-server training.

    The driver-side analog of ``hogwild.train`` (hogwild.py:145-186):
    start the server, run shuffle rounds of per-partition worker
    loops, pull final weights, stop the server (also on error,
    hogwild.py:184-186).

    ``push_every=k`` fuses k minibatch steps into one compiled window
    per push; pulls and the early-stop poll then happen once per
    window, so ``early_stop_patience`` counts k-iteration windows and
    staleness is bounded by one window.

    ``wire`` selects the HTTP wire format: ``'binary'`` (default —
    the framed zero-copy protocol with keep-alive connections and 304
    not-modified pulls) or ``'dill'`` (the reference's pickle wire,
    kept for parity and mixed-version gangs). ``quant='int8'``
    upgrades binary pushes from bf16 to int8 with error-feedback
    residuals; ``compress=False`` ships full-precision pushes on
    either wire.

    ``shards=N`` (with ``transport='http'``) replaces the single
    parameter server with an N-shard fleet
    (:class:`~sparktorch_tpu.serve.fleet.ParamServerFleet`): the
    tensor tree consistent-hashed across N shard servers, workers on
    :class:`~sparktorch_tpu.net.sharded.ShardedTransport` fanning
    per-tensor DELTA pulls and scattered pushes across them.
    ``pull_quant='int8'`` additionally serves int8 pulls with
    server-side error feedback. ``wire='dill'`` with ``shards=N``
    keeps legacy workers working through the fleet's gateway (the
    mixed-version-gang story).

    ``supervise=True`` (or any ``ft_policy``) runs the workers under
    the fault-tolerance supervisor (:mod:`sparktorch_tpu.ft`): a dead
    worker is restarted with exponential backoff + jitter under the
    policy's per-worker budget, and REJOINS by pulling the current
    server version on its first pull — gradients the dead attempt
    already pushed stay applied (hogwild semantics). The restart unit
    is the worker's round assignment (a killed attempt flushes no
    records, so the restarted attempt reruns the round's iterations).
    Recovery is observable as ``ft_restarts_total`` /
    ``ft_recovery_latency_s`` on the run's telemetry bus — the same
    bus ``/metrics`` scrapes.
    """
    tele = telemetry or get_telemetry()
    # Stack sampler beside the ambient ledger: the async trainer's N
    # worker lanes all sample into the same per-process tries, each
    # tagged by the bucket open on ITS thread.
    from sparktorch_tpu.obs import profile as _profile

    _profile.ensure(tele)
    if ft_policy is not None:
        supervise = True
    spec = deserialize_model(torch_obj)
    with tele.span("hogwild/data_prep"):
        train_batch, val_batch = _as_batch(data, labels, validation_pct, seed)
    if spec.input_shape is None:
        spec.input_shape = tuple(np.asarray(train_batch.x).shape[1:])

    devices = jax.devices()
    n_workers = partitions if partitions and partitions > 0 else len(devices)

    if shards and shards > 1 and transport != "http":
        raise ValueError("shards>1 requires transport='http' (the fleet "
                         "is an HTTP tier; local workers need no fleet)")
    # The server records into the SAME run-scoped bus as the workers,
    # so one /metrics scrape (or JSONL dump) tells the whole async
    # story: pulls/pushes/applies next to worker iters and phase times.
    def _restart_counter_total() -> float:
        return sum(
            v for k, v in tele.snapshot().get("counters", {}).items()
            if k.startswith("fleet.shard_restarts_total")
        )

    fleet = None
    restarts_baseline = 0.0
    if shards and shards > 1:
        from sparktorch_tpu.serve.fleet import ParamServerFleet

        # Counters on a shared bus are monotonic across runs; snapshot
        # the baseline so this run's summary reports ITS restarts, not
        # every prior run's on the same process-global bus.
        restarts_baseline = _restart_counter_total()
        server = fleet = ParamServerFleet(
            spec, n_shards=shards,
            window_len=n_workers,  # torch_distributed.py:315-322 parity
            early_stop_patience=early_stop_patience,
            seed=seed, telemetry=tele,
        )
    else:
        server = ParameterServer(
            spec,
            window_len=n_workers,  # torch_distributed.py:315-322 parity
            early_stop_patience=early_stop_patience,
            acquire_lock=acquire_lock,
            seed=seed,
            telemetry=tele,
        )
    http: Optional[ParamServerHttp] = None
    profiler = None
    worker_transports: List[Any] = []
    try:
        if transport == "http" and fleet is not None:
            fleet.start(port=port)
            grace_s = float(getattr(ft_policy, "rejoin_grace_s", 30.0)
                            or 30.0)
            if wire == "dill":
                # Legacy workers keep training through the fleet's
                # gateway — the mixed-version-gang contract.
                worker_transports = [
                    HttpTransport(fleet.gateway_url, compress=compress)
                    for _ in range(n_workers)
                ]
            elif wire == "binary":
                from sparktorch_tpu.net.sharded import ShardedTransport

                push_quant = quant if quant else ("bf16" if compress
                                                  else None)
                worker_transports = [
                    ShardedTransport(fleet, quant=push_quant,
                                     pull_quant=pull_quant,
                                     grace_s=grace_s,
                                     telemetry=tele, run_id=tele.run_id)
                    for _ in range(n_workers)
                ]
            else:
                raise ValueError(
                    f"unknown wire {wire!r}; use 'binary' or 'dill'"
                )
            assert worker_transports[0].alive()  # liveness gate
        elif transport == "http":
            http = ParamServerHttp(server, port=port).start()
            if wire == "dill":
                worker_transports = [
                    HttpTransport(http.url, compress=compress)
                    for _ in range(n_workers)
                ]
            elif wire == "binary":
                push_quant = quant if quant else ("bf16" if compress
                                                  else None)
                worker_transports = [
                    # run_id from the shared run bus: pushes and pulls
                    # carry the run's 16-bit tag in the frame header,
                    # so cross-run traffic (a worker aimed at another
                    # run's recycled port) is counted, never silent.
                    BinaryTransport(http.url, quant=push_quant,
                                    telemetry=tele, run_id=tele.run_id)
                    for _ in range(n_workers)
                ]
            else:
                raise ValueError(
                    f"unknown wire {wire!r}; use 'binary' or 'dill'"
                )
            assert worker_transports[0].alive()  # liveness gate
            # (torch_distributed.py:326 parity)
        else:
            worker_transports = [LocalTransport(server) for _ in range(n_workers)]

        module = spec.make_module()
        grad_step = make_grad_step(module.apply, spec.loss_fn(),
                                   mini_batch=mini_batch)
        grad_windows = make_grad_windows(module.apply, spec.loss_fn(),
                                         mini_batch, push_every, iters)
        eval_loss = (
            make_eval_loss(module.apply, spec.loss_fn())
            if val_batch is not None else None
        )
        model_state = server.model_state()

        records: List[dict] = []
        errors: List[BaseException] = []
        phase_stats: List[dict] = []
        ft_summaries: List[dict] = []
        # N concurrent worker threads each attribute into the ambient
        # goodput ledger (when the caller armed one): each thread is a
        # real execution LANE, so the ledger's MECE budget must be
        # lanes x clock wall — otherwise N threads' attributions read
        # as over-attribution with goodput > 1.
        _ambient = _goodput.active()
        if _ambient is not None:
            _ambient.lanes = max(_ambient.lanes, n_workers)
        x = np.asarray(train_batch.x)
        y = np.asarray(train_batch.y)
        w = np.asarray(train_batch.w)
        shuffle_rng = np.random.default_rng(seed + 1)

        # XLA trace capture around the worker rounds (the same
        # profile_dir contract as the sync/pp trainers); exited in the
        # outer finally so a worker failure still stops the trace.
        profiler = profile_run(profile_dir, telemetry=tele)
        profiler.__enter__()
        for round_idx in range(max(1, partition_shuffles)):
            # EVERY round shuffles, round 0 included: the reference's
            # _fit always repartition()s before training
            # (torch_distributed.py:288-289), redistributing rows
            # across partitions — without that, a label-sorted input
            # becomes single-class workers and async training can
            # collapse to whichever class pushed last (observed as
            # chance accuracy, race-dependent). Minibatch block
            # sampling needs the random resident order anyway.
            perm = shuffle_rng.permutation(x.shape[0])
            x, y, w = x[perm], y[perm], w[perm]  # hogwild.py:161-177
            xs = np.array_split(x, n_workers)
            ys = np.array_split(y, n_workers)
            ws = np.array_split(w, n_workers)
            t_round0 = time.perf_counter()  # lint-obs: ok (round-wall clock)
            worker_args = []
            for i in range(n_workers):
                shard = DataBatch(
                    jnp.asarray(xs[i]), jnp.asarray(ys[i]), jnp.asarray(ws[i])
                )
                worker_args.append((
                    i,
                    devices[i % len(devices)],
                    worker_transports[i],
                    grad_step,
                    model_state,
                    shard,
                    jax.device_put(val_batch, devices[i % len(devices)])
                    if val_batch is not None
                    else None,
                    iters,
                    verbose,
                    early_stop_patience is not None and early_stop_patience > 0,
                    seed + round_idx * n_workers,
                    records,
                ))
            if supervise:
                # The fault-tolerant path: each worker is a supervised
                # task. A dead worker (chaos kill, transport failure,
                # anything the loop surfaces) restarts under the
                # policy's backoff+budget and rejoins by pulling the
                # current server version — a killed attempt flushed no
                # records, so the restarted attempt reruns the round's
                # assignment and the record count stays exact.
                from sparktorch_tpu.ft.supervisor import (
                    Supervisor,
                    ThreadWorker,
                )

                sup = Supervisor(policy=ft_policy, telemetry=tele,
                                 name=f"hogwild_round{round_idx}")

                def make_start(args):
                    def target(cancel):
                        # A fresh error list per attempt: the loop
                        # traps its failure there; re-raising hands it
                        # to the supervisor's handle as THE failure.
                        # `cancel` is the handle's kill() event — the
                        # loop polls it between windows, so straggler
                        # and stall preemption genuinely stop a
                        # thread-based worker.
                        attempt_errors: List[BaseException] = []
                        _worker_loop(*args, attempt_errors, push_every,
                                     eval_loss, grad_windows,
                                     phase_stats, tele, cancel)
                        if attempt_errors:
                            raise attempt_errors[0]

                    return lambda attempt: ThreadWorker(
                        f"w{args[0]}", target, pass_cancel=True
                    )

                for args in worker_args:
                    sup.add(str(args[0]), make_start(args), rank=args[0])
                ft_summaries.append(sup.run())
            else:
                threads = []
                for args in worker_args:
                    t = threading.Thread(
                        target=_worker_loop,
                        args=(*args, errors, push_every, eval_loss,
                              grad_windows, phase_stats, tele),
                        daemon=True,
                    )
                    threads.append(t)
                    t.start()
                for t in threads:
                    t.join()
            tele.observe("hogwild.round_s", time.perf_counter() - t_round0)  # lint-obs: ok (round-wall pair)
            tele.counter("hogwild.rounds")
            if errors:
                raise RuntimeError("hogwild worker failed") from errors[0]
            if server.should_stop:
                break

        params, model_state = server.final_state()
        # The worker pool is joined; there is no dispatch pipeline
        # left to stall.
        # lint-obs: ok (end-of-run gather)
        params = jax.device_get(params)
        model_state = jax.device_get(model_state)  # lint-obs: ok (end-of-run)
        summary = None
        if phase_stats:
            # The budget that sums to the whole: per-phase seconds
            # across workers; other_s is loop bookkeeping (python,
            # record-keeping) not attributed to a phase.
            keys = ("pull_s", "pull_place_s", "dispatch_s",
                    "push_materialize_s", "push_wire_s", "poll_s",
                    "drain_s", "loop_s", "pull_bytes", "push_bytes",
                    "pulls", "pushes", "pull_fresh")
            tot = {k: float(sum(d.get(k, 0) for d in phase_stats))
                   for k in keys}
            tot["other_s"] = tot["loop_s"] - sum(
                tot[k] for k in ("pull_s", "pull_place_s", "dispatch_s",
                                 "push_materialize_s", "push_wire_s",
                                 "poll_s", "drain_s")
            )
            summary = {
                "hogwild_phases": phase_stats,
                "hogwild_budget": tot,
                "server_applied": server.applied_updates,
            }
        if fleet is not None:
            summary = dict(summary or {})
            summary["fleet"] = {
                "shards": len(fleet.urls()),
                "ring_version": fleet.ring_version,
                "shard_restarts": int(_restart_counter_total()
                                      - restarts_baseline),
            }
        if ft_summaries:
            summary = dict(summary or {})
            summary["ft"] = {
                "rounds": ft_summaries,
                "restarts_total": sum(
                    sum(s.get("restarts", {}).values())
                    for s in ft_summaries
                ),
            }
        return TrainResult(
            params=params, model_state=model_state, metrics=records,
            spec=spec, summary=summary,
        )
    finally:
        if profiler is not None:
            profiler.__exit__(None, None, None)
        # Stop server even on failure (hogwild.py:184-186 parity).
        # Transports first: a ShardedTransport owns connections (and
        # possibly a fan-out pool) that must not outlive the run.
        for transport in worker_transports:
            close = getattr(transport, "close", None)
            if close is not None:
                try:
                    close()
                except OSError:
                    pass
        if http is not None:
            http.stop()
        server.stop()


# ---------------------------------------------------------------------------
# Process entry point
# ---------------------------------------------------------------------------


def run_hogwild_worker(torch_obj, url: str, data,
                       labels=None, iters: int = 10,
                       mini_batch: Optional[int] = None,
                       push_every: int = 1, seed: int = 0,
                       worker_id: int = 0, wire: str = "binary",
                       quant: Optional[str] = None,
                       compress: bool = True,
                       records_path: Optional[str] = None,
                       ctx=None) -> dict:
    """ONE hogwild worker as a standalone process — the training third
    of the ``run_shard_server``-shaped entry family, runnable under
    ``python -m sparktorch_tpu.ctl.worker`` with
    ``kind='hogwild_worker'``: pull/push against ``url`` (a param
    server, or a fleet gateway for legacy-topology workers) with its
    own process, GIL, and device context.

    ``data`` is the worker's SHARD: arrays, an ``(x, y)`` tuple, or a
    path to an ``.npz`` with ``x``/``y`` (how a driver ships shards to
    spawned processes without dill-ing arrays through the payload).
    Records flush ATOMICALLY at completion to ``records_path``
    (tmp + rename): a killed attempt publishes nothing, so the
    supervisor-restarted rerun keeps counts exact — the same
    records-exactness contract the thread deployment pins. The ctl
    context's cancel event preempts between windows
    (:class:`~sparktorch_tpu.ft.supervisor.WorkerPreempted`), and its
    heartbeat carries the iteration for skew/stall policies.
    """
    if isinstance(data, str):
        loaded = np.load(data)
        x, y = loaded["x"], loaded["y"]
    elif isinstance(data, tuple) and labels is None:
        x, y = data
    else:
        x, y = data, labels
    spec = deserialize_model(torch_obj)
    if spec.input_shape is None:
        spec.input_shape = tuple(np.asarray(x).shape[1:])
    module = spec.make_module()
    variables = dict(spec.init_params(jax.random.key(seed)))
    variables.pop("params", None)
    model_state = variables or {}
    grad_step = make_grad_step(module.apply, spec.loss_fn(),
                               mini_batch=mini_batch)
    grad_windows = make_grad_windows(module.apply, spec.loss_fn(),
                                     mini_batch, push_every, iters)
    if wire == "binary":
        push_quant = quant if quant else ("bf16" if compress else None)
        transport = BinaryTransport(url, quant=push_quant)
    elif wire == "dill":
        transport = HttpTransport(url, compress=compress)
    else:
        raise ValueError(f"unknown wire {wire!r}; use 'binary' or 'dill'")
    device = jax.devices()[0]
    shard = DataBatch(jnp.asarray(x), jnp.asarray(y),
                      jnp.ones((np.asarray(x).shape[0],), jnp.float32))
    records: List[dict] = []
    errors: List[BaseException] = []
    tele = getattr(ctx, "telemetry", None) or get_telemetry()
    cancel = getattr(ctx, "cancel", None)
    hb = getattr(ctx, "heartbeat", None)
    if hb is not None:
        # Mirror loop progress onto the heartbeat: _worker_loop's
        # telemetry counters already track iters; the heartbeat step
        # is what the supervisor's skew/stall policies read. The real
        # cancel is captured under its own name BEFORE the rebind
        # below — is_set() reading the closure's `cancel` would find
        # the wrapper itself and recurse.
        inner_cancel = cancel

        class _HbCancel:
            """Duck-typed cancel: the loop polls is_set() once per
            window — piggyback the heartbeat step publish there."""

            def is_set(_self) -> bool:
                hb.notify_step(int(tele.snapshot().get("counters", {})
                                   .get(f"hogwild.iters{{worker={worker_id}}}",
                                        0)))
                return (inner_cancel.is_set()
                        if inner_cancel is not None else False)

        cancel = _HbCancel()
    try:
        _worker_loop(worker_id, device, transport, grad_step,
                     model_state, shard, None, iters, 0, False, seed,
                     records, errors, push_every, None, grad_windows,
                     None, tele, cancel)
    finally:
        close = getattr(transport, "close", None)
        if close is not None:
            try:
                close()
            except OSError:
                pass
    if errors:
        raise errors[0]
    if records_path:
        from sparktorch_tpu.obs.sinks import write_jsonl
        import os as _os
        import tempfile as _tempfile

        fd, tmp = _tempfile.mkstemp(
            prefix=".hogwild_records.", suffix=".jsonl",
            dir=_os.path.dirname(records_path) or ".")
        _os.close(fd)
        write_jsonl(tmp, records, append=False)
        _os.replace(tmp, records_path)
    return {"worker_id": worker_id, "iters": iters,
            "records": len(records),
            "final_loss": records[-1]["loss"] if records else None}
