"""GSPMD sharded trainer: dp x fsdp x tp x sp in one jitted step.

The shard_map trainer (:mod:`sparktorch_tpu.train.step`) mirrors the
reference's replicated-model data parallelism. This module is the
scaling path the reference has no analog for (SURVEY §2.4: TP/SP
"absent"): parameters are laid out by sharding rules, the batch is
sharded over dp(+fsdp) and — for sequence models — the sequence axis
over sp; the loss is a global weighted mean, and XLA GSPMD inserts
every collective (tp all-reduces, fsdp all-gathers, dp grad
reduction) over ICI. Ring attention's shard_map island composes
inside this jit (transformer.py).

Run under ``jax.set_mesh(mesh)`` — :func:`make_sharded_train_step`
returns a step already wrapped to do so.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sparktorch_tpu.ft import chaos as _chaos
from sparktorch_tpu.parallel.compat import set_mesh as _set_mesh
from sparktorch_tpu.parallel.mesh import AXIS_SP, BATCH_AXES, replicated
from sparktorch_tpu.parallel.sharding_rules import shard_params, transformer_rules
from sparktorch_tpu.train.step import (
    HealthVec,
    StepMetrics,
    TrainState,
    _accepts_example_w,
    _moe_drop_counts,
    _split_variables,
)
from sparktorch_tpu.utils.data import DataBatch


def batch_specs(seq_sharded: bool) -> DataBatch:
    """PartitionSpecs for (x, y, w). Sequence models shard x/y's
    second dim over sp; targets of LMs are token-level, so y follows
    x's layout when it has a sequence dim."""
    if seq_sharded:
        return DataBatch(
            x=P(BATCH_AXES, AXIS_SP),
            y=P(BATCH_AXES, AXIS_SP),
            w=P(BATCH_AXES),
        )
    return DataBatch(x=P(BATCH_AXES), y=P(BATCH_AXES), w=P(BATCH_AXES))


def create_sharded_state(
    spec,
    mesh: Mesh,
    rng: jax.Array,
    sample_x: jax.Array,
    tx: Optional[optax.GradientTransformation] = None,
    rules: Optional[Callable] = None,
) -> Tuple[TrainState, Any]:
    """Initialize params DIRECTLY into their target shardings: init is
    jitted with out_shardings from the rules, so no host-side full
    materialization ever happens (the driver-OOM-avoidance property of
    the reference's lazy mode, README.md:115-132, done at the XLA
    level)."""
    tx = tx or spec.make_optimizer()
    module = spec.make_module()
    rules = rules or transformer_rules(mesh)

    # The init trace runs the full forward (incl. any shard_map
    # island), so the sample batch must divide across the batch axes.
    import numpy as np

    n_batch_shards = 1
    for ax in BATCH_AXES:
        n_batch_shards *= mesh.shape[ax]
    sample_x = np.asarray(sample_x)
    if sample_x.shape[0] % n_batch_shards != 0:
        reps = -(-n_batch_shards // sample_x.shape[0])
        sample_x = np.tile(sample_x, (reps,) + (1,) * (sample_x.ndim - 1))[
            :n_batch_shards
        ]

    # Everything under set_mesh: tracing the module may hit the ring-
    # attention or MoE-dispatch shard_map islands, which resolve the
    # ambient mesh.
    #
    # Layout-invariant init is a PARITY requirement: the default
    # (non-partitionable) threefry lowering makes a jitted init's
    # draws depend on the out_shardings, so an ep-sharded expert
    # weight started at DIFFERENT values on an ep=2 mesh than on ep=1
    # — the dominant term of the historical ~0.7% ep-parity drift
    # (the MoE suite now pins ep=2 vs ep=1 at rtol 1e-5, which is
    # impossible without this). Scoped tightly to the init jit: the
    # train step itself draws no randoms, and the flag changes draw
    # VALUES, so leaking it process-wide would silently shift every
    # other trainer's seeds — hence set INSIDE the try whose finally
    # restores it.
    _old_threefry = jax.config.jax_threefry_partitionable
    try:
        jax.config.update("jax_threefry_partitionable", True)
        with _set_mesh(mesh):
            abstract = jax.eval_shape(lambda k: module.init(k, sample_x), rng)
            # _split_variables drops the write-only 'losses' collection
            # (sown aux objectives), which must never live in the carried
            # train state — see step().
            a_params, a_state = _split_variables(abstract)
            param_sh = shard_params(a_params, mesh, rules)
            state_sh = jax.tree.map(lambda _: replicated(mesh), a_state)

            def init_all(key):
                variables = module.init(key, sample_x)
                params, mstate = _split_variables(variables)
                opt_state = tx.init(params)
                return params, mstate, opt_state

            a_opt = jax.eval_shape(lambda k: init_all(k)[2], rng)
            opt_sh = _opt_state_shardings(a_opt, a_params, param_sh, mesh)

            params, mstate, opt_state = jax.jit(
                init_all, out_shardings=(param_sh, state_sh, opt_sh)
            )(rng)
    finally:
        jax.config.update("jax_threefry_partitionable", _old_threefry)
    state = TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        model_state=mstate,
        opt_state=opt_state,
        rng=rng,
    )
    shardings = TrainState(
        step=replicated(mesh),
        params=param_sh,
        model_state=state_sh,
        opt_state=opt_sh,
        rng=replicated(mesh),
    )
    return state, shardings


def _opt_state_shardings(a_opt, a_params, param_sh, mesh: Mesh):
    """Optimizer-state leaves that mirror a param leaf (same shape)
    inherit its sharding; scalars/others replicate. Keeps Adam moments
    sharded like their params (fsdp/tp) — the memory win that matters."""
    shape_map = {}
    for leaf, sh in zip(jax.tree.leaves(a_params), jax.tree.leaves(param_sh)):
        shape_map.setdefault((tuple(leaf.shape), str(leaf.dtype)), sh)

    def pick(leaf):
        key = (tuple(getattr(leaf, "shape", ())), str(getattr(leaf, "dtype", "")))
        return shape_map.get(key, replicated(mesh))

    return jax.tree.map(pick, a_opt)


# Env knob for the auto path's persistent-compile-cache arming:
# unset/1 arms (default ~/.cache/sparktorch_tpu/xla), 0/off disables,
# any other value is the cache directory.
XLA_CACHE_ENV = "SPARKTORCH_TPU_XLA_CACHE"


def _make_finish(loop_state):
    """The shared ``run.finish()`` for both auto paths (GSPMD and
    pipeline winners): end an in-flight XLA capture, return the
    published :class:`TraceAnalysis` (or None), and upgrade an active
    goodput ledger's comm model to 'measured' from the analysis."""
    from sparktorch_tpu.obs import goodput as _goodput

    def finish():
        profiler, loop_state["profiler"] = loop_state["profiler"], None
        if profiler is not None:
            profiler.__exit__(None, None, None)
        handle, loop_state["handle"] = loop_state["handle"], None
        analysis = handle["analysis"] if handle else None
        ledger = _goodput.active()
        if ledger is not None and analysis is not None:
            ledger.apply_analysis(analysis)
        return analysis

    return finish


def _maybe_arm_xla_cache() -> bool:
    """Arm the jax persistent compilation cache for ``mesh='auto'``
    builds (see :func:`sparktorch_tpu.utils.checkpoint.
    arm_persistent_cache` for the restore-safety rules)."""
    import os

    env = (os.environ.get(XLA_CACHE_ENV) or "").strip()
    if env in ("0", "off", "false"):
        return False
    if env in ("", "1", "true", "on"):
        cache_dir = os.path.join(os.path.expanduser("~"), ".cache",
                                 "sparktorch_tpu", "xla")
    else:
        cache_dir = env
    from sparktorch_tpu.utils.checkpoint import arm_persistent_cache

    return arm_persistent_cache(cache_dir)


def _make_auto_pipeline_step(spec, tx, mesh, tune_result, rng,
                             sample_batch: DataBatch,
                             profile_dir: Optional[str] = None,
                             telemetry=None):
    """Build the ``mesh='auto'`` fast path for a PIPELINE winner: the
    tuner picked a pp>1 candidate (``tune_result.best_schedule`` names
    the schedule / virtual_stages / n_micro it measured), so the
    returned ``run`` dispatches through
    :func:`sparktorch_tpu.train.pipeline.make_pp_train_step` — the
    same schedule path the candidate was measured through — with the
    usual auto extras (``run.state`` is the initial
    :class:`~sparktorch_tpu.train.pipeline.PipelineState`,
    ``run.mesh``, ``run.tune_result``, ``run.finish``) plus
    ``run.pipeline_schedule`` (the schedule meta) and
    ``run.eval_loss``. Batches fed to ``run`` must keep rows
    divisible by dp x n_micro (the sample batch the tuner measured
    already is). MoE winners with ep>1 get the a2a grouping opt-in
    threaded through the built step (``pp_moe_group_size``), so the
    production step runs the same dispatch layout the measured
    candidate did."""
    import numpy as np

    from sparktorch_tpu.obs import get_telemetry
    from sparktorch_tpu.obs import goodput as _goodput
    from sparktorch_tpu.train.pipeline import (
        PipelineState,
        build_pp_schedule_step,
    )

    meta = dict(tune_result.best_schedule or {})
    if not meta:
        raise ValueError(
            "pp>1 tune winner carries no schedule meta — re-run the "
            "search (pre-schedule cache entries are fenced by the "
            "cache-key schema bump)"
        )
    rows = int(sample_batch.x.shape[0])
    seq = (int(sample_batch.x.shape[1])
           if np.asarray(sample_batch.x).ndim >= 2 else 1)
    # The ONE shared build recipe (validation, head pick, MoE a2a
    # group opt-in, restack + interleave + placement) — the same path
    # the tuner measured the winner through.
    auto_state, step, _cfg, _head = build_pp_schedule_step(
        spec, mesh, meta, rows, seq, tx=tx, rng=rng,
        sample_x=sample_batch.x[:1],
    )

    from sparktorch_tpu.utils.tracing import profile_run, step_annotation

    tele = telemetry or get_telemetry()
    loop_state = {"calls": 0, "profiler": None, "handle": None}
    est_comm_fraction = None
    ranking = tune_result.ranking()
    if ranking and ranking[0].measured:
        est_comm_fraction = float(
            ranking[0].measured.get("exposed_comm_fraction", 0.0))

    def run(state: PipelineState, batch: DataBatch):
        if profile_dir and loop_state["profiler"] is None:
            loop_state["profiler"] = profile_run(profile_dir,
                                                 telemetry=tele)
            loop_state["handle"] = loop_state["profiler"].__enter__()
        step_no = loop_state["calls"]
        loop_state["calls"] += 1
        ledger = _goodput.active()
        if ledger is None:
            with tele.span("train_sharded/step"), \
                    step_annotation(step_no, telemetry=tele):
                return step(state, batch)
        # Same ledger contract as the GSPMD run: synced step span,
        # re-aimed at ``compile`` when the schedule's jit dispatch
        # cache grew under the call (the winner's fresh-closure
        # recompile lands on the TuneResult's compile bill).
        if est_comm_fraction is not None:
            ledger.set_comm_model(est_comm_fraction, "estimate")
        # Straggler injection before the step span: the skew referee
        # must see a late fence arrival, not a longer step.
        _chaos.straggle(jax.process_index(), step_no)
        cache0 = step.jit_cache_size()
        with tele.span("train_sharded/step"), \
                step_annotation(step_no, telemetry=tele):
            with ledger.step_span(step=step_no) as led:
                out = step(state, batch)
                cache1 = step.jit_cache_size()
                if cache0 is not None and cache1 is not None \
                        and cache1 > cache0:
                    led.rebucket("compile")
                elif cache0 is None and cache1 is not None \
                        and cache1 > 0 and step_no == 0:
                    # First call: the probe reads None before the
                    # lazily-built jitted exists, so a grown cache
                    # after the call IS the compile signal.
                    led.rebucket("compile")
                jax.block_until_ready(out[1])
        if led.bucket == "compile":
            tele.counter("goodput.compiles_total",
                         labels={"site": "train_sharded"})
            tune_result.compile_count += 1
            tune_result.compile_s_total += float(led.duration_s)
        return out

    run.jitted = None              # pipeline jit is lazily built
    run.mesh = mesh
    run.finish = _make_finish(loop_state)
    run.state = auto_state
    run.shardings = None           # pipeline layout lives in the step
    run.tune_result = tune_result
    run.pipeline_schedule = meta
    run.pipeline_step = step
    run.eval_loss = step.eval_loss
    return run


def make_sharded_train_step(
    apply_fn: Callable,
    loss_fn: Callable,
    tx: optax.GradientTransformation,
    mesh,
    state_shardings: Optional[TrainState] = None,
    seq_sharded: bool = False,
    profile_dir: Optional[str] = None,
    telemetry=None,
    spec=None,
    sample_batch: Optional[DataBatch] = None,
    rng: Optional[jax.Array] = None,
    tune_kwargs: Optional[dict] = None,
) -> Callable[[TrainState, DataBatch], Tuple[TrainState, StepMetrics]]:
    """One GSPMD train step: global weighted-mean loss and grads; XLA
    derives every collective from the shardings.

    ``mesh`` is a concrete :class:`jax.sharding.Mesh` — or the string
    ``"auto"``: the trace-guided auto-tuner
    (:func:`sparktorch_tpu.parallel.tune.autotune`) searches the legal
    mesh space for ``spec`` on ``sample_batch`` (both required in auto
    mode; ``tune_kwargs`` forwards search knobs like ``measure_top_k``
    or ``artifact_path``) and the winner becomes the mesh. The auto
    path also initializes the train state INTO the winning layout, so
    the returned ``run`` exposes ``run.state`` (the initial
    :class:`TrainState`), ``run.shardings``, and ``run.tune_result``
    beside the usual ``run.mesh`` — callers start the loop from
    ``run.state`` instead of calling :func:`create_sharded_state`
    themselves (the mesh was not known until now). When the tuner's
    winner has pp>1 the returned ``run`` is a PIPELINE-scheduled step
    instead (same contract; ``run.state`` is a ``PipelineState``,
    ``run.pipeline_schedule`` names the winning schedule — see
    :func:`_make_auto_pipeline_step`). CONTRACT: that pipeline step
    derives its apply/loss from ``spec`` (head-typed cross entropy,
    like every train_distributed pp dispatch), NOT from the
    ``apply_fn``/``loss_fn`` arguments — the search only opens pp
    when ``spec.loss`` is in the cross-entropy family, so callers
    passing a loss_fn that does not match their spec's loss must pin
    ``tune_kwargs={'axes': GSPMD_AXES}`` to stay on the GSPMD path.
    Known cost: the
    winner's GSPMD program compiles once inside the tuner's
    measurement and once more for this fresh step closure (jit cannot
    dedupe across closures) — amortized over a training run; RE-runs
    of the same (workload, rig) skip the whole search via the
    tune-result cache (on by default here; ``tune_kwargs={'cache':
    False}`` or ``SPARKTORCH_TPU_TUNE_CACHE=0`` opts out, and the
    artifact records ``cache_hit``).

    Telemetry/tracing (same contract as the sync/pp trainers'
    ``profile_dir``): every call of the returned ``run`` carries a
    per-step trace annotation and a ``train_sharded/step`` span on the
    bus. With ``profile_dir`` set, the FIRST call starts an XLA
    profiler trace there; the caller owns the loop here (no trainer
    driver), so it ends the capture with ``run.finish()`` — also safe
    to call when no profile was requested. Stopping the capture
    auto-analyzes it (:mod:`sparktorch_tpu.obs.xprof`): per-step
    collective/compute attribution lands on the bus as ``xprof.*``
    metrics, and ``finish()`` returns the :class:`TraceAnalysis`
    (None when nothing was captured).
    """
    tune_result = None
    auto_state: Optional[TrainState] = None
    if isinstance(mesh, str):
        if mesh != "auto":
            raise ValueError(f"mesh must be a Mesh or 'auto', got {mesh!r}")
        if spec is None or sample_batch is None:
            raise ValueError(
                "mesh='auto' needs spec= and sample_batch= (the tuner "
                "measures candidate meshes on a representative batch)"
            )
        from sparktorch_tpu.parallel.mesh import build_mesh
        from sparktorch_tpu.parallel.tune import autotune

        # The tuner, the winning mesh, and the state layout must all
        # see the SAME device set — a tune_kwargs={'devices': ...}
        # subset would otherwise pick a config whose axis product no
        # longer matches jax.devices().
        tune_kwargs = dict(tune_kwargs or {})
        devices = tune_kwargs.pop("devices", None) or jax.devices()
        # Re-runs of the same workload on the same rig load the
        # cached winner instead of re-searching (and re-compiling
        # every candidate) — SPARKTORCH_TPU_TUNE_CACHE=0 opts out,
        # tune_kwargs={'cache': False} opts out per call.
        tune_kwargs.setdefault("cache", True)
        # Recompile tax (ROADMAP 4b): arm the PERSISTENT compile cache
        # for the auto path, so the winner's known second compile (the
        # tuner's measurement closure, then this fresh step closure —
        # jit cannot dedupe across closures) is a disk hit instead of
        # a full XLA compile, and the next process warm-starts the
        # whole search's compiles. SPARKTORCH_TPU_XLA_CACHE=0 opts
        # out; a path value relocates the cache dir. arm_persistent_
        # cache refuses after an orbax restore (the restore <->
        # cache-mediated-collective SIGABRT its disarm hook exists
        # for) and defers to an already-configured cache dir.
        _maybe_arm_xla_cache()
        tune_result = autotune(
            spec, sample_batch, devices, tx=tx, seq_sharded=seq_sharded,
            telemetry=telemetry, **tune_kwargs,
        )
        mesh = build_mesh(tune_result.best_config(), devices)
        if int(tune_result.best.get("pp", 1)) > 1:
            # The winner is a PIPELINE schedule: hand back a
            # pipeline-scheduled step (same run/finish/introspection
            # contract) instead of forcing the mesh through the
            # schedule-less GSPMD trainer.
            return _make_auto_pipeline_step(
                spec, tx, mesh, tune_result,
                rng if rng is not None else jax.random.key(0),
                sample_batch, profile_dir=profile_dir,
                telemetry=telemetry,
            )
        auto_state, state_shardings = create_sharded_state(
            spec, mesh,
            rng if rng is not None else jax.random.key(0),
            sample_x=sample_batch.x[:1], tx=tx,
        )
    if state_shardings is None:
        raise ValueError("state_shardings is required unless mesh='auto'")

    pass_w = _accepts_example_w(apply_fn)

    def step(state: TrainState, batch: DataBatch):
        def weighted_mean_loss(params):
            variables = {"params": params, **state.model_state}
            # 'losses'/'moe_metrics' are write-only: requested mutable
            # every step so sow() records fresh values, but never
            # carried in the train state (sow APPENDS to carried-in
            # collections, which would grow the pytree every step).
            mutable = [*state.model_state.keys(), "losses", "moe_metrics"]
            kwargs = {"example_w": batch.w} if pass_w else {}
            preds, new_state = apply_fn(variables, batch.x, mutable=mutable,
                                        **kwargs)
            new_state = dict(new_state)
            sown = new_state.pop("losses", None)
            sown_metrics = new_state.pop("moe_metrics", None)
            if not state.model_state:
                new_state = state.model_state
            per = loss_fn(preds, batch.y)
            num = jnp.sum(per * batch.w)
            den = jnp.maximum(jnp.sum(batch.w), 1.0)
            loss = num / den
            # Sown auxiliary objectives (e.g. the MoE load-balance
            # loss, already weighted at the sow site) join the task
            # loss so their gradients flow.
            if sown is not None:
                for leaf in jax.tree.leaves(sown):
                    loss = loss + jnp.sum(leaf).astype(loss.dtype)
            return loss, (den, new_state, _moe_drop_counts(sown_metrics))

        (loss, (den, new_model_state, drops)), grads = jax.value_and_grad(
            weighted_mean_loss, has_aux=True
        )(state.params)
        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        new_state = TrainState(
            step=state.step + 1,
            params=new_params,
            model_state=new_model_state,
            opt_state=new_opt,
            rng=state.rng,
        )
        # GSPMD computes over GLOBAL arrays, so the sown counters are
        # already global sums — no extra collective needed.
        gnorm = optax.global_norm(grads)
        grad_leaves = jax.tree.leaves(grads)
        leaf_norms = (
            jnp.stack([jnp.sqrt(jnp.sum(jnp.square(g))).astype(jnp.float32)
                       for g in grad_leaves])
            if grad_leaves else jnp.zeros((0,), jnp.float32)
        )
        metrics = StepMetrics(
            loss=loss, examples=den, grad_norm=gnorm,
            drop_fraction=(drops[0] / jnp.maximum(drops[1], 1.0)
                           if drops is not None else None),
            health=HealthVec(
                finite=(jnp.isfinite(loss)
                        & jnp.isfinite(gnorm)).astype(jnp.float32),
                update_ratio=optax.global_norm(updates)
                / jnp.maximum(optax.global_norm(new_params), 1e-12),
                leaf_norms=leaf_norms,
            ),
        )
        return new_state, metrics

    b_specs = batch_specs(seq_sharded)
    in_shardings = (
        state_shardings,
        DataBatch(*(NamedSharding(mesh, s) for s in b_specs)),
    )
    jitted = jax.jit(
        step,
        in_shardings=in_shardings,
        out_shardings=(state_shardings, None),
        donate_argnums=(0,),
    )

    from sparktorch_tpu.obs import get_telemetry
    from sparktorch_tpu.obs import goodput as _goodput
    from sparktorch_tpu.obs import health as _health
    from sparktorch_tpu.obs import profile as _stackprof
    from sparktorch_tpu.utils.tracing import profile_run, step_annotation

    tele = telemetry or get_telemetry()
    # Stack sampler beside the ambient ledger (see train/sync.py) —
    # the caller owns the loop here, so the step factory is where
    # "wherever ledgers live" lands for the GSPMD path.
    _stackprof.ensure(tele)
    _health.ensure(tele)

    def _feed_health(out) -> None:
        # Everything queues as DEVICE values (including loss/grad_norm
        # — this path never host-syncs them itself); the ledger's
        # K-late drain does the one attributed readback.
        hl = _health.active()
        if hl is None:
            return
        m = out[1]
        dev = {"loss": m.loss, "grad_norm": m.grad_norm}
        if m.health is not None:
            dev.update(finite=m.health.finite,
                       update_ratio=m.health.update_ratio,
                       leaf_norms=m.health.leaf_norms)
        hl.note_step(device=dev)
    loop_state = {"calls": 0, "profiler": None, "handle": None}
    # The comm model the goodput ledger starts under: the tuner's
    # measured exposed fraction for the winning mesh when the auto
    # path ran (a labeled ESTIMATE here — it was measured in the
    # search's capture, not this run's), upgraded to "measured" when
    # finish() analyzes a capture of THIS run.
    est_comm_fraction = None
    if tune_result is not None:
        ranking = tune_result.ranking()
        if ranking and ranking[0].measured:
            est_comm_fraction = float(
                ranking[0].measured.get("exposed_comm_fraction", 0.0))

    def run(state, batch):
        if profile_dir and loop_state["profiler"] is None:
            loop_state["profiler"] = profile_run(profile_dir, telemetry=tele)
            loop_state["handle"] = loop_state["profiler"].__enter__()
        step_no = loop_state["calls"]
        loop_state["calls"] += 1
        ledger = _goodput.active()
        if ledger is None:
            with _set_mesh(mesh), tele.span("train_sharded/step"), \
                    step_annotation(step_no, telemetry=tele):
                out = jitted(state, batch)
            _feed_health(out)
            return out
        # Ledger-armed path: the call is timed as a step span, synced
        # (async dispatch without a sync measures enqueue, not compute
        # — the ROUND4 honest-timing lesson), and re-bucketed to
        # ``compile`` when the jit dispatch cache GREW under it (the
        # first call, a new input shape, or the auto path's known
        # winner recompile).
        if est_comm_fraction is not None:
            ledger.set_comm_model(est_comm_fraction, "estimate")
        # Straggler injection before the step span (late fence
        # arrival, attributable by the skew referee).
        _chaos.straggle(jax.process_index(), step_no)
        cache0 = _goodput.jit_cache_size(jitted)
        with _set_mesh(mesh), tele.span("train_sharded/step"), \
                step_annotation(step_no, telemetry=tele):
            with ledger.step_span(step=step_no) as led:
                out = jitted(state, batch)
                cache1 = _goodput.jit_cache_size(jitted)
                if cache0 is not None and cache1 is not None \
                        and cache1 > cache0:
                    led.rebucket("compile")
                jax.block_until_ready(out[1].loss)
        if led.bucket == "compile":
            tele.counter("goodput.compiles_total",
                         labels={"site": "train_sharded"})
            if tune_result is not None:
                # The auto path's documented "compiles its winner
                # twice" cost, finally a number: the fresh step
                # closure's recompile lands on the SAME TuneResult the
                # artifact was stamped from.
                tune_result.compile_count += 1
                tune_result.compile_s_total += float(led.duration_s)
        _feed_health(out)
        return out

    # Introspection hooks (tests assert on the compiled HLO — e.g. that
    # the MoE layout constraints actually lower to all-to-alls).
    run.jitted = jitted
    run.mesh = mesh
    run.finish = _make_finish(loop_state)
    # Auto-tune extras (None unless mesh="auto"): the initial state in
    # the winning layout, its shardings, and the search record.
    run.state = auto_state
    run.shardings = state_shardings
    run.tune_result = tune_result
    return run


def shard_batch(batch: DataBatch, mesh: Mesh, seq_sharded: bool = False) -> DataBatch:
    specs = batch_specs(seq_sharded)
    return DataBatch(
        *(jax.device_put(a, NamedSharding(mesh, s)) for a, s in zip(batch, specs))
    )
