"""GPipe pipeline parallelism over the ``pp`` mesh axis, composable
with tensor parallelism over ``tp``.

No reference counterpart (SURVEY §2.4: PP "absent"). TPU-first
design: the transformer stack is split into ``pp`` stages — the
stacked per-layer params are sharded over ``pp`` on their leading
(layer) dim — and a ``shard_map`` step runs the classic GPipe
schedule: microbatches enter at stage 0, activations hop stage→stage
on an ICI ring via ``lax.ppermute``, the last stage accumulates the
weighted loss, and autodiff THROUGH the schedule (ppermute transposes
to the reverse permute) yields exact gradients — mathematically
identical to gradient accumulation over the microbatches on one
device, which is what the parity test asserts.

The whole schedule (M + S - 1 ticks) is one ``lax.scan`` inside one
jitted ``shard_map``: zero per-tick Python, static shapes, and the
bubble is the textbook (S-1)/(M+S-1) fraction — raise ``n_micro`` to
shrink it.

Within a stage the encoder layer is computed in explicit einsum form
(same math and param tree as ``models.transformer.EncoderLayer``) so
that:

- **tp composes**: attention heads and FFN columns are sliced over the
  ``tp`` axis, with the classic Megatron f/g pair implemented as
  custom-vjp ops (:func:`_tp_enter`: identity forward / psum backward
  at the entry of each parallel region; :func:`_tp_reduce`: psum
  forward / identity backward at its exit). With those two ops every
  parameter gradient is complete and tp-identical without any
  tp-axis gradient reduction.
- **remat works**: each layer's forward is wrapped in
  ``jax.checkpoint`` when ``cfg.remat`` — activations recompute in the
  backward pass, the standard memory/FLOPs trade for deep stacks.
- **flash attention works**: ``attn_impl='flash'`` calls the Pallas
  streaming kernel on the local heads (a kernel is a primitive, not a
  nested shard_map, so it composes with the pp schedule; ring
  attention's own shard_map island does not and stays rejected).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, NamedTuple, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sparktorch_tpu.models.transformer import EncoderLayer, TransformerConfig
from sparktorch_tpu.ops.attention import dense_attention
from sparktorch_tpu.parallel.mesh import AXIS_DP, AXIS_PP, AXIS_TP
from sparktorch_tpu.train.step import shard_map_compat
from sparktorch_tpu.utils.data import DataBatch


class PipelineState(NamedTuple):
    step: jax.Array
    params: Any
    opt_state: Any


# ---------------------------------------------------------------------------
# Megatron-style f/g for tensor parallelism (exact grads, no tp-axis
# gradient reductions needed anywhere).
# ---------------------------------------------------------------------------


@jax.custom_vjp
def _tp_enter(x):
    """Entry of a tp-parallel region: identity forward, psum backward.
    Makes cotangents on the replicated stream complete (summed over
    every head/column slice) and tp-identical."""
    return x


def _tp_enter_fwd(x):
    return x, None


def _tp_enter_bwd(_, ct):
    return (jax.lax.psum(ct, AXIS_TP),)


_tp_enter.defvjp(_tp_enter_fwd, _tp_enter_bwd)


@jax.custom_vjp
def _tp_reduce(x):
    """Exit of a tp-parallel region: psum forward, identity backward
    (each slice receives the full output cotangent)."""
    return jax.lax.psum(x, AXIS_TP)


def _tp_reduce_fwd(x):
    return jax.lax.psum(x, AXIS_TP), None


def _tp_reduce_bwd(_, ct):
    return (ct,)


_tp_reduce.defvjp(_tp_reduce_fwd, _tp_reduce_bwd)


# ---------------------------------------------------------------------------
# Stage math (EncoderLayer's exact param tree, explicit einsum form)
# ---------------------------------------------------------------------------


def _ln(p, x, dt):
    xf = x.astype(jnp.float32)
    mean = xf.mean(-1, keepdims=True)
    var = ((xf - mean) ** 2).mean(-1, keepdims=True)
    xf = (xf - mean) / jnp.sqrt(var + 1e-6)
    return (xf * p["scale"] + p["bias"]).astype(dt)


def _layer_forward(cfg: TransformerConfig, lp, h):
    """One encoder layer on this device's head/column slice.

    ``lp`` is the layer's param tree with ``qkv``/``proj``/``mlp``
    kernels already SLICED over tp (shard_map did that); ln params and
    output-side biases arrive replicated. Replicated output-side
    biases are added AFTER :func:`_tp_reduce` (once, undivided): the
    cotangent there is the full output cotangent on every slice, so
    their gradients come out complete and tp-identical with no
    reduction — adding a 1/tp-scaled bias inside the reduce instead
    would silently shrink those gradients by tp (caught by the SGD
    grad-parity test).
    """
    dt = cfg.compute_dtype
    a = _tp_enter(_ln(lp["ln_attn"], h, dt))
    qkv_k = lp["attn"]["qkv"]["kernel"].astype(dt)     # (d, 3, h_loc, hd)
    qkv_b = lp["attn"]["qkv"]["bias"].astype(dt)       # (3, h_loc, hd)
    qkv = jnp.einsum("bsd,dthf->bsthf", a, qkv_k) + qkv_b
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]  # (b, s, h_loc, hd)
    if cfg.attn_impl == "flash":
        from sparktorch_tpu.ops.flash_attention import flash_attention

        out = flash_attention(q, k, v, cfg.causal)
    else:
        out = dense_attention(q, k, v, causal=cfg.causal)
    proj_k = lp["attn"]["proj"]["kernel"].astype(dt)   # (h_loc, hd, d)
    proj_b = lp["attn"]["proj"]["bias"].astype(dt)     # (d,) replicated
    attn_out = _tp_reduce(jnp.einsum("bshf,hfd->bsd", out, proj_k)) + proj_b
    x = h + attn_out

    m = _tp_enter(_ln(lp["ln_mlp"], x, dt))
    w1 = lp["mlp_in"]["kernel"].astype(dt)             # (d, ff_loc)
    b1 = lp["mlp_in"]["bias"].astype(dt)               # (ff_loc,)
    mid = nn.gelu(m @ w1 + b1)
    w2 = lp["mlp_out"]["kernel"].astype(dt)            # (ff_loc, d)
    b2 = lp["mlp_out"]["bias"].astype(dt)              # (d,) replicated
    return x + _tp_reduce(mid @ w2) + b2


def _moe_pattern(cfg: TransformerConfig):
    """Per-layer use_moe flags — delegates to the ONE schedule
    definition on the config (shared with the flax Transformer)."""
    return cfg.moe_pattern()


def _stacked_layer_init(cfg, key, use_moe: bool, n: int):
    layer = EncoderLayer(cfg, use_moe=use_moe)
    sample_h = jnp.zeros((1, cfg.max_len, cfg.d_model), cfg.compute_dtype)
    keys = jax.random.split(key, n)
    return jax.vmap(lambda k: layer.init(k, sample_h)["params"])(keys)


def _init_backbone(cfg: TransformerConfig, k_embed, k_pos, k_dense, k_moe):
    """Shared pipeline backbone init: embeddings, final norm, and the
    dense / MoE layer stacks (separate stacks — their trees differ;
    each pp-sharded on its leading layer dim)."""
    pattern = _moe_pattern(cfg)
    n_dense = pattern.count(False)
    n_moe = pattern.count(True)
    d = cfg.d_model
    params = {
        "tok_embed": jax.random.normal(k_embed, (cfg.vocab_size, d)) * 0.02,
        "pos_embed": jax.random.normal(k_pos, (cfg.max_len, d)) * 0.02,
        "ln_scale": jnp.ones((d,)),
        "ln_bias": jnp.zeros((d,)),
    }
    if n_dense:
        params["layers"] = _stacked_layer_init(cfg, k_dense, False, n_dense)
    if n_moe:
        params["layers_moe"] = _stacked_layer_init(cfg, k_moe, True, n_moe)
    return params


def init_pipeline_lm(cfg: TransformerConfig, key: jax.Array):
    """Host-side init of a causal LM laid out for pipelining: the
    encoder layers' params are STACKED on a leading (layer) dim — the
    dim the pp sharding splits — plus replicated embedding / final
    norm / LM head tensors."""
    cfg = dataclasses.replace(cfg, causal=True)
    k_embed, k_pos, k_head, k_dense, k_moe = jax.random.split(key, 5)
    d = cfg.d_model
    params = _init_backbone(cfg, k_embed, k_pos, k_dense, k_moe)
    params["head_w"] = jax.random.normal(k_head, (d, cfg.vocab_size)) * (
        1.0 / np.sqrt(d)
    )
    params["head_b"] = jnp.zeros((cfg.vocab_size,))
    return params


def init_pipeline_classifier(cfg: TransformerConfig, key: jax.Array):
    """Pipeline layout of the BERT-style ``SequenceClassifier``: same
    stacked layers + embedding, with a pooler (tanh) + classifier head
    instead of the LM head."""
    k_embed, k_pos, k_pool, k_cls, k_dense, k_moe = jax.random.split(key, 6)
    d = cfg.d_model
    params = _init_backbone(cfg, k_embed, k_pos, k_dense, k_moe)
    params["pool_w"] = jax.random.normal(k_pool, (d, d)) * (1.0 / np.sqrt(d))
    params["pool_b"] = jnp.zeros((d,))
    params["cls_w"] = jax.random.normal(k_cls, (d, cfg.n_classes)) * (
        1.0 / np.sqrt(d)
    )
    params["cls_b"] = jnp.zeros((cfg.n_classes,))
    return params


# Per-leaf tp sharding of the stacked layer tree, keyed by the dim the
# head/column slice lives on (after the leading layer-stack dim).
_TP_LAYER_DIMS = {
    ("attn", "qkv", "kernel"): 3,   # (L, d, 3, h, hd) -> heads
    ("attn", "qkv", "bias"): 2,     # (L, 3, h, hd)
    ("attn", "proj", "kernel"): 1,  # (L, h, hd, d)
    ("mlp_in", "kernel"): 2,        # (L, d, ff)
    ("mlp_in", "bias"): 1,          # (L, ff)
    ("mlp_out", "kernel"): 1,       # (L, ff, d)
}


def _layer_leaf_spec(path_names: Tuple[str, ...], ndim: int) -> P:
    """Spec for one stacked-layer leaf: pp on the stack dim, tp on the
    leaf's head/column dim when it has one."""
    for key, dim in _TP_LAYER_DIMS.items():
        if path_names[-len(key):] == key:
            parts = [AXIS_PP] + [None] * (ndim - 1)
            parts[dim] = AXIS_TP
            return P(*parts)
    return P(AXIS_PP)


def _param_specs(params) -> Any:
    """Per-leaf PartitionSpecs: layer stacks split over pp on their
    leading (layer) dim and over tp on head/column dims; MoE layer
    stacks split over pp only (experts replicated within a stage — tp
    is rejected with MoE); everything else replicated."""
    from jax.tree_util import tree_map_with_path

    def layers_spec(path, leaf):
        names = tuple(
            str(getattr(p, "key", getattr(p, "name", p))) for p in path
        )
        return _layer_leaf_spec(names, np.ndim(leaf))

    return {
        k: (
            tree_map_with_path(layers_spec, v)
            if k == "layers"
            else jax.tree.map(lambda _: P(AXIS_PP), v)
            if k == "layers_moe"
            else jax.tree.map(lambda _: P(), v)
        )
        for k, v in params.items()
    }


def place_pipeline_state(params, tx, mesh: Mesh) -> PipelineState:
    """device_put params into their pipeline layout and init the
    optimizer on the placed arrays. EVERY leaf (incl. optimizer
    scalars and the step counter) gets an explicit mesh-wide
    sharding: eager optax init would otherwise leave scalar leaves on
    one device, and a checkpoint restored against those shardings
    could not feed the pp shard_map step."""
    specs = _param_specs(params)
    sh = jax.tree.map(
        lambda s: NamedSharding(mesh, s), specs,
        is_leaf=lambda x: isinstance(x, P),
    )
    params = jax.tree.map(jax.device_put, params, sh)
    opt_state = tx.init(params)
    opt_sh = jax.tree.map(
        lambda s: NamedSharding(mesh, s), _opt_specs(tx, opt_state, specs),
        is_leaf=lambda x: isinstance(x, P),
    )
    opt_state = jax.tree.map(jax.device_put, opt_state, opt_sh)
    return PipelineState(
        step=jax.device_put(jnp.zeros((), jnp.int32),
                            NamedSharding(mesh, P())),
        params=params,
        opt_state=opt_state,
    )


def make_pp_train_step(
    cfg: TransformerConfig,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    n_micro: int,
    head: str = "lm",
) -> Callable[[PipelineState, DataBatch], Tuple[PipelineState, jax.Array]]:
    """Build the jitted pipelined train step over ``mesh`` (dp x pp x
    tp; other axes must be 1 for this trainer).

    ``head``: ``'lm'`` (next-token CE over the vocab, causal) or
    ``'classifier'`` (BERT-style pooler + class CE — the config-4
    workload, pipelined)."""
    if head not in ("lm", "classifier"):
        raise ValueError(f"unknown head {head!r}")
    for ax in mesh.shape:
        if ax not in (AXIS_DP, AXIS_PP, AXIS_TP) and mesh.shape[ax] != 1:
            raise ValueError(
                f"pipeline trainer supports dp x pp x tp only; {ax}>1"
            )
    S = mesh.shape[AXIS_PP]
    T = mesh.shape[AXIS_TP]
    if cfg.n_layers % max(1, S) != 0:
        raise ValueError(f"n_layers={cfg.n_layers} not divisible by pp={S}")
    if cfg.n_heads % max(1, T) != 0:
        raise ValueError(f"n_heads={cfg.n_heads} not divisible by tp={T}")
    if cfg.d_ff % max(1, T) != 0:
        raise ValueError(f"d_ff={cfg.d_ff} not divisible by tp={T}")
    # MoE composes when every stage sees the SAME dense/MoE layer
    # pattern (the two layer kinds live in separate pp-sharded
    # stacks); experts replicate within a stage — expert PARALLELISM
    # stays the GSPMD trainer's ep axis.
    pattern = _moe_pattern(cfg)
    has_moe = any(pattern)
    if has_moe:
        if T > 1:
            raise ValueError(
                "pp x tp with MoE layers is not supported (experts "
                "replicate within a stage); use tp=1, or the GSPMD "
                "sharded trainer with the ep axis for expert parallelism"
            )
        lps = cfg.n_layers // max(1, S)
        stage_patterns = [pattern[s * lps:(s + 1) * lps] for s in range(S)]
        if any(sp != stage_patterns[0] for sp in stage_patterns):
            raise ValueError(
                f"MoE layer pattern {pattern} is not uniform across "
                f"pp={S} stages; choose moe_every/n_layers so every "
                "stage holds the same dense/MoE sequence"
            )
        stage_pattern = stage_patterns[0]
    if cfg.attn_impl == "ring":
        # ring opens its own shard_map island, which does not compose
        # with the pp shard_map schedule.
        raise ValueError(
            "pipeline trainer supports attn_impl 'dense' or 'flash' "
            "(ring attention's shard_map island does not nest)"
        )
    if head == "lm":
        cfg = dataclasses.replace(cfg, causal=True)
    dt = cfg.compute_dtype

    layer_fwd = lambda lp, h: _layer_forward(cfg, lp, h)
    if cfg.remat:
        layer_fwd = jax.checkpoint(layer_fwd)

    def stage_fn(local_layers, h):
        def body(h, lp):
            return layer_fwd(lp, h), None

        h, _ = jax.lax.scan(body, h, local_layers)
        return h

    if has_moe:
        from sparktorch_tpu.train.step import _moe_drop_counts

        moe_layer = EncoderLayer(cfg, use_moe=True)

        def moe_apply(lp, h, token_w):
            out, sown = moe_layer.apply(
                {"params": lp}, h, token_w,
                mutable=["losses", "moe_metrics"],
            )
            aux = jnp.zeros((), jnp.float32)
            for leaf in jax.tree.leaves(sown.get("losses", {})):
                aux = aux + jnp.sum(leaf).astype(jnp.float32)
            counts = _moe_drop_counts(sown.get("moe_metrics"))
            dropped, routed = counts if counts is not None else (
                jnp.zeros(()), jnp.zeros(())
            )
            return out, aux, dropped, routed

        if cfg.remat:
            moe_apply = jax.checkpoint(moe_apply)

        def stage_fn_moe(params, h, token_w):
            """Unrolled stage walk over the per-stage pattern, picking
            each layer's params from its kind's pp-sharded stack."""
            aux = jnp.zeros((), jnp.float32)
            dropped = jnp.zeros((), jnp.float32)
            routed = jnp.zeros((), jnp.float32)
            jd = jm = 0
            for is_moe in stage_pattern:
                if is_moe:
                    lp = jax.tree.map(lambda a: a[jm], params["layers_moe"])
                    h, a, dr, rt = moe_apply(lp, h, token_w)
                    aux = aux + a
                    dropped = dropped + dr
                    routed = routed + rt
                    jm += 1
                else:
                    lp = jax.tree.map(lambda a: a[jd], params["layers"])
                    h = layer_fwd(lp, h)
                    jd += 1
            return h, aux, dropped, routed

    def embed(params, ids):
        s = ids.shape[1]
        h = params["tok_embed"][ids] + params["pos_embed"][None, :s]
        return h.astype(dt)

    def head_loss(params, h, y, w):
        hf = _ln({"scale": params["ln_scale"], "bias": params["ln_bias"]},
                 h, jnp.float32)
        if head == "classifier":
            # Pooler in the model's compute dtype, classifier logits in
            # f32 — matching the flax SequenceClassifier exactly
            # (transformer.py: pooler Dense dtype=compute_dtype,
            # classifier Dense dtype=float32), so pp-trained params see
            # the same numerics the module applies at transform time.
            pooled = jnp.tanh(
                hf.astype(dt).mean(1) @ params["pool_w"].astype(dt)
                + params["pool_b"].astype(dt)
            )
            logits = (pooled.astype(jnp.float32) @ params["cls_w"]
                      + params["cls_b"])
            per_ex = optax.softmax_cross_entropy_with_integer_labels(logits, y)
        else:
            logits = hf @ params["head_w"] + params["head_b"]
            per_tok = optax.softmax_cross_entropy_with_integer_labels(logits, y)
            per_ex = per_tok.mean(-1)
        return jnp.sum(per_ex * w), jnp.sum(w)

    ring = [(i, (i + 1) % S) for i in range(S)]

    def schedule_loss(params, x, y, w):
        """The full GPipe schedule's global weighted-mean loss (plus
        the MoE aux term and drop fraction) — differentiated by
        local_step, called forward-only by the eval step."""
        stage = jax.lax.axis_index(AXIS_PP)
        b_local, s = x.shape
        if b_local % n_micro != 0:
            raise ValueError(
                f"local batch {b_local} not divisible by n_micro={n_micro}"
            )
        mb = b_local // n_micro
        micro_x = x.reshape(n_micro, mb, s)
        # lm targets are token-level (b, s); classifier labels (b,).
        micro_y = y.reshape((n_micro, mb) + y.shape[1:])
        micro_w = w.reshape(n_micro, mb)

        def pipeline_loss(params):
            def tick(carry, t):
                h_prev, num, den, aux, dropped, routed = carry
                inj = jnp.clip(t, 0, n_micro - 1)
                # Only stage 0 embeds and only the last stage (inside
                # its valid drain window) runs the vocab-sized head —
                # lax.cond skips the dead branch at runtime instead of
                # computing it everywhere and masking to zero (the
                # head matmul + its backward dominate for real vocabs).
                h_in = jax.lax.cond(
                    stage == 0,
                    lambda: embed(params, micro_x[inj]),
                    lambda: h_prev,
                )
                if has_moe:
                    # The microbatch THIS stage processes at tick t was
                    # injected at t - stage; bubble ticks (no valid
                    # microbatch) get all-zero token weights so their
                    # garbage activations never touch routing, capacity
                    # or the aux loss.
                    m_in = t - stage
                    mi_in = jnp.clip(m_in, 0, n_micro - 1)
                    valid_in = ((m_in >= 0) & (m_in < n_micro)).astype(
                        micro_w.dtype
                    )
                    tw = jnp.broadcast_to(
                        (micro_w[mi_in] * valid_in)[:, None], (mb, s)
                    )
                    h_out, aux_t, dr_t, rt_t = stage_fn_moe(params, h_in, tw)
                    aux = aux + aux_t
                    dropped = dropped + dr_t
                    routed = routed + rt_t
                else:
                    h_out = stage_fn(params["layers"], h_in)
                m = t - (S - 1)
                mi = jnp.clip(m, 0, n_micro - 1)
                use = (m >= 0) & (m < n_micro) & (stage == S - 1)
                n_, d_ = jax.lax.cond(
                    use,
                    lambda: head_loss(params, h_out, micro_y[mi], micro_w[mi]),
                    lambda: (jnp.zeros(()), jnp.zeros(())),
                )
                num = num + n_
                den = den + d_
                h_next = jax.lax.ppermute(h_out, AXIS_PP, ring)
                return (h_next, num, den, aux, dropped, routed), None

            init_h = jnp.zeros((mb, s, cfg.d_model), dt)
            zero = jnp.zeros(())
            (_, num, den, aux, dropped, routed), _ = jax.lax.scan(
                tick,
                (init_h, zero, zero, zero, zero, zero),
                jnp.arange(n_micro + S - 1),
            )
            num_g = jax.lax.psum(num, (AXIS_PP, AXIS_DP))
            den_g = jax.lax.psum(den, (AXIS_PP, AXIS_DP))
            task = num_g / jnp.maximum(den_g, 1.0)
            loss = task
            if has_moe:
                # Sum over stages/layers (psum pp — stages hold
                # disjoint MoE layers), mean over microbatches and dp
                # shards: the pipelined analog of the GSPMD trainer's
                # batch-mean sown aux.
                aux_g = jax.lax.psum(aux, (AXIS_PP, AXIS_DP))
                dp_n = jax.lax.axis_size(AXIS_DP)
                loss = loss + aux_g / (n_micro * dp_n)
                dropped_g = jax.lax.psum(dropped, (AXIS_PP, AXIS_DP))
                routed_g = jax.lax.psum(routed, (AXIS_PP, AXIS_DP))
                drop_fraction = dropped_g / jnp.maximum(routed_g, 1.0)
            else:
                drop_fraction = jnp.zeros(())
            # aux pair: (drop_fraction, task-only loss) — the eval
            # path reports the task loss (the DP eval excludes sown
            # aux objectives from the validation signal too).
            return loss, (drop_fraction, task)

        return pipeline_loss(params)

    def local_step(params, opt_state, x, y, w):
        (loss, (drop_fraction, _)), grads = jax.value_and_grad(
            lambda p: schedule_loss(p, x, y, w), has_aux=True
        )(params)
        # Replicated-param grads must be summed over every axis the
        # param is replicated across: layer stacks live on one pp
        # shard each (sum over dp only); embed/head/norm are used on
        # all stages (masked elsewhere -> zero grads) and replicated
        # over both axes. No tp reductions anywhere: the f/g pair in
        # _layer_forward already makes every grad complete and
        # tp-identical.
        grads = {
            k: (
                jax.tree.map(lambda g: jax.lax.psum(g, AXIS_DP), v)
                if k in ("layers", "layers_moe")
                else jax.tree.map(
                    lambda g: jax.lax.psum(g, (AXIS_PP, AXIS_DP)), v
                )
            )
            for k, v in grads.items()
        }
        updates, new_opt = tx.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        return new_params, new_opt, loss, drop_fraction

    cache = {}

    def _build_eval(specs):
        """Forward-only schedule for validation: same pipeline, no
        grads, reporting the TASK loss (the [1][1] aux slot — sown MoE
        aux objectives are excluded from the validation signal, like
        the DP eval)."""
        eval_mapped = shard_map_compat(
            lambda p, x, y, w: schedule_loss(p, x, y, w)[1][1],
            mesh,
            in_specs=(specs, P(AXIS_DP), P(AXIS_DP), P(AXIS_DP)),
            out_specs=P(),
        )
        return jax.jit(eval_mapped)

    def step(state: PipelineState, batch: DataBatch):
        if "jitted" not in cache:
            specs = _param_specs(state.params)
            opt_specs = _opt_specs(tx, state.opt_state, specs)
            mapped = shard_map_compat(
                local_step,
                mesh,
                in_specs=(specs, opt_specs,
                          P(AXIS_DP), P(AXIS_DP), P(AXIS_DP)),
                out_specs=(specs, opt_specs, P(), P()),
            )
            cache["jitted"] = jax.jit(mapped, donate_argnums=(0, 1))
            cache["eval"] = _build_eval(specs)
        new_params, new_opt, loss, drop = cache["jitted"](
            state.params, state.opt_state, batch.x, batch.y, batch.w
        )
        # Introspection hook (concrete post-jit value): the MoE
        # capacity-drop fraction for this step; the training entry
        # records it as moe_drop_fraction like the other trainers.
        step.last_drop_fraction = float(drop) if has_moe else None
        return (
            PipelineState(step=state.step + 1, params=new_params,
                          opt_state=new_opt),
            loss,
        )

    def eval_loss(state: PipelineState, batch: DataBatch):
        if "eval" not in cache:
            cache["eval"] = _build_eval(_param_specs(state.params))
        return cache["eval"](state.params, batch.x, batch.y, batch.w)

    step.eval_loss = eval_loss
    return step


def _opt_specs(tx, opt_state, param_specs):
    """Optimizer leaves that mirror the param TREE (Adam moments etc.)
    inherit the matching param's spec exactly — structural matching
    via ``optax.tree_map_params``, not shape heuristics (two params
    can share a shape); every non-param leaf replicates."""
    return optax.tree_map_params(
        tx,
        lambda _, spec: spec,
        opt_state,
        param_specs,
        transform_non_params=lambda _: P(),
        is_leaf=lambda x: isinstance(x, P),
    )


# ---------------------------------------------------------------------------
# ModelSpec / estimator integration: pp as a mesh-config choice
# ---------------------------------------------------------------------------


def pipeline_params_from_flax(params, cfg: TransformerConfig):
    """Convert a ``CausalLM`` (untied) or ``SequenceClassifier`` flax
    param tree into the pipeline's stacked layout (dense and MoE
    layers into their separate stacks). Inverse of
    :func:`flax_params_from_pipeline`."""
    bb = params["backbone"]
    pattern = _moe_pattern(cfg)
    out = {
        "tok_embed": bb["tok_embed"]["embedding"],
        "pos_embed": bb["pos_embed"],
        "ln_scale": bb["ln_final"]["scale"],
        "ln_bias": bb["ln_final"]["bias"],
    }
    dense = [bb[f"layer_{i}"] for i in range(cfg.n_layers) if not pattern[i]]
    moe = [bb[f"layer_{i}"] for i in range(cfg.n_layers) if pattern[i]]
    if dense:
        out["layers"] = jax.tree.map(lambda *xs: jnp.stack(xs), *dense)
    if moe:
        out["layers_moe"] = jax.tree.map(lambda *xs: jnp.stack(xs), *moe)
    if "lm_head" in params:
        out["head_w"] = params["lm_head"]["kernel"]
        out["head_b"] = params["lm_head"]["bias"]
    else:
        out["pool_w"] = params["pooler"]["kernel"]
        out["pool_b"] = params["pooler"]["bias"]
        out["cls_w"] = params["classifier"]["kernel"]
        out["cls_b"] = params["classifier"]["bias"]
    return out


def flax_params_from_pipeline(pparams, cfg: TransformerConfig):
    """Back to the ``CausalLM`` / ``SequenceClassifier`` flax tree (so
    the fitted bundle transforms through the ordinary module apply)."""
    pattern = _moe_pattern(cfg)
    bb = {}
    jd = jm = 0
    for i in range(cfg.n_layers):
        if pattern[i]:
            k = jm
            bb[f"layer_{i}"] = jax.tree.map(
                lambda a, k=k: a[k], pparams["layers_moe"]
            )
            jm += 1
        else:
            k = jd
            bb[f"layer_{i}"] = jax.tree.map(
                lambda a, k=k: a[k], pparams["layers"]
            )
            jd += 1
    bb["tok_embed"] = {"embedding": pparams["tok_embed"]}
    bb["pos_embed"] = pparams["pos_embed"]
    bb["ln_final"] = {"scale": pparams["ln_scale"],
                      "bias": pparams["ln_bias"]}
    if "head_w" in pparams:
        return {
            "backbone": bb,
            "lm_head": {"kernel": pparams["head_w"],
                        "bias": pparams["head_b"]},
        }
    return {
        "backbone": bb,
        "pooler": {"kernel": pparams["pool_w"], "bias": pparams["pool_b"]},
        "classifier": {"kernel": pparams["cls_w"], "bias": pparams["cls_b"]},
    }


def train_distributed_pipeline(
    spec,
    data,
    labels=None,
    mesh: Optional[Mesh] = None,
    iters: int = 10,
    n_micro: int = 4,
    verbose: int = 0,
    seed: int = 0,
    metrics_hook=None,
    checkpoint_dir: Optional[str] = None,
    checkpoint_every: int = 0,
    resume: bool = False,
    partition_shuffles: int = 1,
    early_stop_patience: int = -1,
    validation_pct: float = 0.0,
):
    """Pipelined training entry for a ``ModelSpec`` holding a
    ``CausalLM`` — the dispatch target ``train_distributed`` uses when
    the mesh has pp > 1, so pp is a MESH choice on the ordinary
    Estimator/ModelSpec surface, not a separate API.

    The spec's flax params are initialized normally, restacked into
    the pipeline layout, trained under the GPipe schedule, and
    unstacked back — the returned ``TrainResult`` bundles ordinary
    ``CausalLM`` params that transform through the module apply.
    """
    import time

    from sparktorch_tpu.models.transformer import CausalLM, SequenceClassifier
    from sparktorch_tpu.train.sync import TrainResult
    from sparktorch_tpu.utils.metrics import MetricsRecorder

    module = spec.make_module()
    if isinstance(module, CausalLM):
        head = "lm"
    elif isinstance(module, SequenceClassifier):
        head = "classifier"
    else:
        raise ValueError(
            "pipeline-parallel training (mesh pp>1) supports CausalLM "
            f"and SequenceClassifier specs; got {type(module).__name__}. "
            "Use a mesh with pp=1 for other model families."
        )
    cfg = module.config
    if cfg.tie_embeddings:
        raise ValueError("pp training does not support tie_embeddings yet")
    if spec.loss not in ("cross_entropy", "cross_entropy_fused", "nll"):
        raise ValueError(
            f"pp training uses cross entropy; got {spec.loss!r}"
        )

    if isinstance(data, DataBatch):
        x = np.asarray(data.x)
        y = np.asarray(data.y)
        w = np.asarray(data.w, dtype=np.float32)
    elif (isinstance(data, tuple) and len(data) == 2 and labels is None):
        # The (x, y) tuple form _as_batch accepts on the pp=1 path.
        x = np.asarray(data[0])
        y = np.asarray(data[1])
        w = np.ones((x.shape[0],), np.float32)
    else:
        x = np.asarray(data)
        y = np.asarray(labels) if labels is not None else None
        if y is None:
            if head == "classifier":
                raise ValueError("classifier pp training requires labels")
            x, y = x[:, :-1], x[:, 1:]  # next-token LM on one id matrix
        w = np.ones((x.shape[0],), np.float32)
    x = x.astype(np.int32)
    y = y.astype(np.int32)

    from sparktorch_tpu.utils.data import pad_to_multiple

    dp = mesh.shape[AXIS_DP]
    need = dp * n_micro

    def _pad_batch(bx, by, bw):
        return pad_to_multiple(
            DataBatch(x=jnp.asarray(bx), y=jnp.asarray(by),
                      w=jnp.asarray(bw)),
            need,
        )

    val_batch = None
    if validation_pct and validation_pct > 0:
        # Split BEFORE padding (the reference's per-worker holdout,
        # util.py:81-95): a shuffled cut of real rows, keeping any
        # caller-supplied sample weights.
        perm0 = np.random.default_rng(seed).permutation(x.shape[0])
        n_val = max(1, int(x.shape[0] * validation_pct))
        val_idx, train_idx = perm0[:n_val], perm0[n_val:]
        if train_idx.size == 0:
            raise ValueError("validation_pct leaves no training rows")
        val_batch = _pad_batch(x[val_idx], y[val_idx], w[val_idx])
        x, y, w = x[train_idx], y[train_idx], w[train_idx]
    n = int(np.sum(w > 0))
    batch = _pad_batch(x, y, w)
    n_rows_padded = int(batch.x.shape[0])

    tx = spec.make_optimizer()
    # Build the step FIRST: its config validation (stage divisibility,
    # MoE pattern uniformity, tp x MoE) produces actionable errors;
    # placement would otherwise fail earlier with a raw sharding error.
    step = make_pp_train_step(cfg, tx, mesh, n_micro=n_micro, head=head)
    rng = jax.random.key(seed)
    flax_params = dict(spec.init_params(rng, sample_x=x[:1]))["params"]
    pparams = pipeline_params_from_flax(flax_params, cfg)
    state = place_pipeline_state(pparams, tx, mesh)

    from sparktorch_tpu.train.sync import (
        _finalize_checkpoint,
        _open_checkpoint,
        _save_if_due,
    )

    # PipelineState checkpoints like TrainState (step-indexed orbax
    # snapshots restored INTO the pp/tp-sharded layout).
    ckpt, state = _open_checkpoint(checkpoint_dir, resume, state)

    from sparktorch_tpu.utils.early_stopper import EarlyStopping

    stopper = (
        EarlyStopping(patience=early_stop_patience)
        if early_stop_patience is not None and early_stop_patience > 0
        else None
    )
    recorder = MetricsRecorder(n_chips=mesh.size)
    last_ckpt = int(jax.device_get(state.step)) if ckpt is not None else 0
    start = int(jax.device_get(state.step))
    # Seed folded with the restored step: a resumed run must draw
    # FRESH permutations, not replay the interrupted run's (same
    # invariant as the streaming trainer's resume seeding).
    shuffle_rng = np.random.default_rng(seed + 1 + start)
    # On-device permutation: one small index upload per round instead
    # of re-uploading the full x/y/w arrays from the host.
    permute = jax.jit(
        lambda b, p: DataBatch(x=b.x[p], y=b.y[p], w=b.w[p])
    )
    completed = False
    stop = False
    try:
        for shuffle_round in range(max(1, partition_shuffles)):
            if shuffle_round > 0:
                # The reference's partition reshuffle between rounds
                # (distributed.py:267-273): microbatch membership
                # changes; weight-0 padding rows stay masked wherever
                # they land.
                batch = permute(
                    batch,
                    jnp.asarray(shuffle_rng.permutation(n_rows_padded)),
                )
            for i in range(iters):
                t0 = time.perf_counter()
                state, loss = step(state, batch)
                val_loss = (
                    float(step.eval_loss(state, val_batch))
                    if val_batch is not None else None
                )
                record = {
                    "round": shuffle_round, "iter": i,
                    "loss": float(loss), "val_loss": val_loss,
                    "examples": float(n), "grad_norm": float("nan"),
                    "step_time_s": time.perf_counter() - t0,
                }
                drop = getattr(step, "last_drop_fraction", None)
                if drop is not None:
                    record["moe_drop_fraction"] = drop
                recorder.record(record)
                if metrics_hook:
                    metrics_hook(record)
                if verbose:
                    msg = (f"[sparktorch_tpu:pp] round {shuffle_round} "
                           f"iter {i} loss {float(loss):.6f}")
                    if val_loss is not None:
                        msg += f" val_loss {val_loss:.6f}"
                    print(msg)
                last_ckpt = _save_if_due(ckpt, state, last_ckpt,
                                         checkpoint_every)
                # The global loss is replicated on every host, so the
                # per-host stopper reaches the identical decision (no
                # extra collective — same argument as the DP trainer).
                if stopper is not None and stopper.step(
                    val_loss if val_loss is not None else float(loss)
                ):
                    stop = True
                    break
            if stop:
                break
        completed = True
    finally:
        _finalize_checkpoint(ckpt, state, completed)

    trained = jax.device_get(state.params)
    out_params = flax_params_from_pipeline(trained, cfg)
    return TrainResult(params=out_params, model_state={},
                       metrics=recorder.records, spec=spec,
                       summary=recorder.summary())
