"""Pipeline parallelism over the ``pp`` mesh axis (GPipe and 1F1B
schedules), composable with tensor parallelism over ``tp`` and —
for MoE stacks — expert parallelism over ``ep``.

No reference counterpart (SURVEY §2.4: PP "absent"). TPU-first
design: the transformer stack is split into ``pp`` stages — the
stacked per-layer params are sharded over ``pp`` on their leading
(layer) dim — and a ``shard_map`` step runs the schedule:
microbatches enter at stage 0, activations hop stage→stage on an ICI
ring via ``lax.ppermute``, the last stage accumulates the weighted
loss.

Two schedules, identical math (exactness-tested against each other):

- ``gpipe`` (default): the whole schedule (M + S - 1 ticks) is one
  ``lax.scan`` and autodiff THROUGH it (ppermute transposes to the
  reverse permute) yields exact gradients; activation memory scales
  with M (the scan saves per-tick carries).
- ``1f1b``: a combined-tick 1F1B schedule (M + 2S - 2 ticks) with a
  MANUAL backward — each backward tick re-runs its stage forward
  under ``jax.vjp``, so only the stage inputs of in-flight
  microbatches persist, in a ring of 2S - 1 slots: activation memory
  scales with S, not M (measured via XLA memory_analysis in the
  tests). FLOPs match remat-GPipe. MoE stacks (and ep sharding)
  compose — the aux loss and drop counts ride the manual backward.

Zero per-tick Python, static shapes; the GPipe bubble is the textbook
(S-1)/(M+S-1) fraction — raise ``n_micro`` to shrink it.

Within a stage the encoder layer is computed in explicit einsum form
(same math and param tree as ``models.transformer.EncoderLayer``) so
that:

- **tp composes**: attention heads and FFN columns are sliced over the
  ``tp`` axis, with the classic Megatron f/g pair implemented as
  custom-vjp ops (:func:`_tp_enter`: identity forward / psum backward
  at the entry of each parallel region; :func:`_tp_reduce`: psum
  forward / identity backward at its exit). With those two ops every
  parameter gradient is complete and tp-identical without any
  tp-axis gradient reduction.
- **remat works**: each layer's forward is wrapped in
  ``jax.checkpoint`` when ``cfg.remat`` — activations recompute in the
  backward pass, the standard memory/FLOPs trade for deep stacks.
- **flash attention works**: ``attn_impl='flash'`` calls the Pallas
  streaming kernel on the local heads (a kernel is a primitive, not a
  nested shard_map, so it composes with the pp schedule).
- **sp composes**: with ``attn_impl='ring'`` the sequence dim shards
  over ``sp`` and ring attention runs as a plain ``ppermute`` K/V
  rotation INSIDE the schedule's shard_map (no nested island). The
  per-example loss mean and the classifier pooling cross sp through
  :func:`_sp_reduce` (psum forward / identity backward), so every
  param grad stays an honest per-shard share that one psum over sp
  completes.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Callable, NamedTuple, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sparktorch_tpu.models.transformer import EncoderLayer, TransformerConfig
from sparktorch_tpu.obs import goodput as _goodput
from sparktorch_tpu.parallel.compat import axis_size as _axis_size
from sparktorch_tpu.ops.attention import dense_attention
from sparktorch_tpu.parallel.mesh import (
    AXIS_DP,
    AXIS_EP,
    AXIS_PP,
    AXIS_SP,
    AXIS_TP,
)
from sparktorch_tpu.train.step import shard_map_compat
from sparktorch_tpu.utils.data import DataBatch


class PipelineState(NamedTuple):
    step: jax.Array
    params: Any
    opt_state: Any


class PpStepOut(NamedTuple):
    """Per-step arrays from a fused multi-schedule call
    (``steps_per_call > 1``), each shaped ``(k,)``."""

    loss: jax.Array
    drop_fraction: Optional[jax.Array]
    grad_norm: jax.Array
    examples: jax.Array


# ---------------------------------------------------------------------------
# Megatron-style f/g for tensor parallelism (exact grads, no tp-axis
# gradient reductions needed anywhere).
# ---------------------------------------------------------------------------


@jax.custom_vjp
def _tp_enter(x):
    """Entry of a tp-parallel region: identity forward, psum backward.
    Makes cotangents on the replicated stream complete (summed over
    every head/column slice) and tp-identical."""
    return x


def _tp_enter_fwd(x):
    return x, None


def _tp_enter_bwd(_, ct):
    return (jax.lax.psum(ct, AXIS_TP),)


_tp_enter.defvjp(_tp_enter_fwd, _tp_enter_bwd)


@jax.custom_vjp
def _tp_reduce(x):
    """Exit of a tp-parallel region: psum forward, identity backward
    (each slice receives the full output cotangent)."""
    return jax.lax.psum(x, AXIS_TP)


def _tp_reduce_fwd(x):
    return jax.lax.psum(x, AXIS_TP), None


def _tp_reduce_bwd(_, ct):
    return (ct,)


_tp_reduce.defvjp(_tp_reduce_fwd, _tp_reduce_bwd)


@jax.custom_vjp
def _ep_enter(x):
    """Entry of the expert-parallel path: identity forward, psum-over-
    ep backward. Each ep member's expert-path input-cotangent covers
    only ITS experts' share; summing them here makes the cotangent
    leaving the MoE FFN complete and ep-identical, so every upstream
    gradient (attn, ln, dense layers, embeddings) keeps the ordinary
    replicated-over-ep reductions."""
    return x


def _ep_enter_fwd(x):
    return x, None


def _ep_enter_bwd(_, ct):
    return (jax.lax.psum(ct, AXIS_EP),)


_ep_enter.defvjp(_ep_enter_fwd, _ep_enter_bwd)


@jax.custom_vjp
def _ep_reduce(x):
    """Exit of the expert-parallel path: psum forward (combine the
    per-member partial expert outputs), identity backward (each member
    receives the full output cotangent ONCE — a raw psum would
    transpose to another psum and double-count it; same trap the tp
    f/g pair guards)."""
    return jax.lax.psum(x, AXIS_EP)


def _ep_reduce_fwd(x):
    return jax.lax.psum(x, AXIS_EP), None


def _ep_reduce_bwd(_, ct):
    return (ct,)


_ep_reduce.defvjp(_ep_reduce_fwd, _ep_reduce_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def _a2a_ep(x, split_axis: int, concat_axis: int):
    """Tiled all_to_all over ``ep`` with an explicit reverse-exchange
    backward. The op is linear, so its true VJP is the inverse
    exchange (swap split/concat axes); spelling it as a custom_vjp
    keeps the pp schedules' autodiff (GPipe's grad-through-scan and
    1F1B's per-tick ``jax.vjp``) off jax's all_to_all transpose path,
    which miscompiles for split != concat (verified on jax 0.9)."""
    return jax.lax.all_to_all(x, AXIS_EP, split_axis, concat_axis,
                              tiled=True)


def _a2a_ep_fwd(x, split_axis, concat_axis):
    return _a2a_ep(x, split_axis, concat_axis), None


def _a2a_ep_bwd(split_axis, concat_axis, _, ct):
    return (jax.lax.all_to_all(ct, AXIS_EP, concat_axis, split_axis,
                               tiled=True),)


_a2a_ep.defvjp(_a2a_ep_fwd, _a2a_ep_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def _ep_scatter(x, g_loc: int):
    """This member's block of ``g_loc`` leading-dim entries of an
    ep-REPLICATED array (block m for ep member m). Backward:
    all_gather of the per-member cotangent blocks — the assembled
    cotangent is complete and identical on every member, so gradients
    upstream of the scatter stay ep-replicated (each block counted
    exactly once; a transpose-of-slice alone would leave per-member
    partial cotangents)."""
    i = jax.lax.axis_index(AXIS_EP)
    return jax.lax.dynamic_slice_in_dim(x, i * g_loc, g_loc, 0)


def _ep_scatter_fwd(x, g_loc):
    return _ep_scatter(x, g_loc), None


def _ep_scatter_bwd(g_loc, _, ct):
    return (jax.lax.all_gather(ct, AXIS_EP, axis=0, tiled=True),)


_ep_scatter.defvjp(_ep_scatter_fwd, _ep_scatter_bwd)


@jax.custom_vjp
def _ep_gather(x):
    """Inverse of :func:`_ep_scatter`: all_gather the members' blocks
    into the full ep-replicated array. Backward: each member keeps its
    OWN block of the incoming cotangent — not a reduce_scatter: the
    downstream computation is ep-replicated, so every member already
    holds the full cotangent and summing over members would scale it
    by ep (the same trap the psum/psum pair guards)."""
    return jax.lax.all_gather(x, AXIS_EP, axis=0, tiled=True)


def _ep_gather_fwd(x):
    return _ep_gather(x), None


def _ep_gather_bwd(_, ct):
    n_ep = _axis_size(AXIS_EP)
    g_loc = ct.shape[0] // n_ep
    i = jax.lax.axis_index(AXIS_EP)
    return (jax.lax.dynamic_slice_in_dim(ct, i * g_loc, g_loc, 0),)


_ep_gather.defvjp(_ep_gather_fwd, _ep_gather_bwd)


@jax.custom_vjp
def _sp_reduce(x):
    """Exit of a sequence-parallel region: psum over ``sp`` forward
    (combine the per-member partial sums over their sequence shards),
    identity backward — each member receives the full output cotangent
    exactly once, so its upstream (per-token) gradients are its true
    per-shard share and the trainer's psum over sp completes them. The
    sp twin of the Megatron ``_tp_reduce`` g-op."""
    return jax.lax.psum(x, AXIS_SP)


def _sp_reduce_fwd(x):
    return jax.lax.psum(x, AXIS_SP), None


def _sp_reduce_bwd(_, ct):
    return (ct,)


_sp_reduce.defvjp(_sp_reduce_fwd, _sp_reduce_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def _scale_grad(x, factor: float):
    """Identity forward, cotangent scaled by ``factor`` backward. Used
    on parameters whose forward inputs are REPLICATED across a mesh
    axis the trainer later psums their gradient over (the classifier
    head under sp: pooling makes its input sp-replicated, so each sp
    member computes the FULL head gradient and the sp psum would
    overcount by sp — scaling by 1/sp makes the psum exact)."""
    return x


def _scale_grad_fwd(x, factor):
    return x, None


def _scale_grad_bwd(factor, _, ct):
    return (jax.tree.map(lambda c: c * factor, ct),)


_scale_grad.defvjp(_scale_grad_fwd, _scale_grad_bwd)


# ---------------------------------------------------------------------------
# Stage math (EncoderLayer's exact param tree, explicit einsum form)
# ---------------------------------------------------------------------------


def _ln(p, x, dt):
    xf = x.astype(jnp.float32)
    mean = xf.mean(-1, keepdims=True)
    var = ((xf - mean) ** 2).mean(-1, keepdims=True)
    xf = (xf - mean) / jnp.sqrt(var + 1e-6)
    return (xf * p["scale"] + p["bias"]).astype(dt)


def _attn_half(cfg: TransformerConfig, lp, h):
    """ln_attn -> attention -> proj residual: the first half of
    :func:`_layer_forward`, shared with the MoE layer path (whose FFN
    half is an expert dispatch instead of the dense MLP)."""
    dt = cfg.compute_dtype
    a = _tp_enter(_ln(lp["ln_attn"], h, dt))
    qkv_k = lp["attn"]["qkv"]["kernel"].astype(dt)     # (d, 3, h_loc, hd)
    qkv_b = lp["attn"]["qkv"]["bias"].astype(dt)       # (3, h_loc, hd)
    qkv = jnp.einsum("bsd,dthf->bsthf", a, qkv_k) + qkv_b
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]  # (b, s, h_loc, hd)
    if cfg.attn_impl == "flash":
        from sparktorch_tpu.ops.flash_attention import flash_attention

        out = flash_attention(q, k, v, cfg.causal)
    elif cfg.attn_impl == "ring":
        # Ring attention expressed IN the pp shard_map (VERDICT r04
        # item 4): the schedule's shard_map binds every mesh axis, so
        # the K/V rotation is a plain ppermute over ``sp`` here — no
        # nested shard_map island. Composes with tp (per-head) and
        # both schedules (ppermute transposes exactly under GPipe
        # autodiff; the 1F1B per-tick vjp re-runs it).
        from sparktorch_tpu.ops.attention import ring_attention

        out = ring_attention(q, k, v, axis_name=AXIS_SP, causal=cfg.causal)
    else:
        out = dense_attention(q, k, v, causal=cfg.causal)
    proj_k = lp["attn"]["proj"]["kernel"].astype(dt)   # (h_loc, hd, d)
    proj_b = lp["attn"]["proj"]["bias"].astype(dt)     # (d,) replicated
    return h + _tp_reduce(jnp.einsum("bshf,hfd->bsd", out, proj_k)) + proj_b


def _layer_forward(cfg: TransformerConfig, lp, h):
    """One encoder layer on this device's head/column slice.

    ``lp`` is the layer's param tree with ``qkv``/``proj``/``mlp``
    kernels already SLICED over tp (shard_map did that); ln params and
    output-side biases arrive replicated. Replicated output-side
    biases are added AFTER :func:`_tp_reduce` (once, undivided): the
    cotangent there is the full output cotangent on every slice, so
    their gradients come out complete and tp-identical with no
    reduction — adding a 1/tp-scaled bias inside the reduce instead
    would silently shrink those gradients by tp (caught by the SGD
    grad-parity test).
    """
    dt = cfg.compute_dtype
    x = _attn_half(cfg, lp, h)
    m = _tp_enter(_ln(lp["ln_mlp"], x, dt))
    w1 = lp["mlp_in"]["kernel"].astype(dt)             # (d, ff_loc)
    b1 = lp["mlp_in"]["bias"].astype(dt)               # (ff_loc,)
    mid = nn.gelu(m @ w1 + b1)
    w2 = lp["mlp_out"]["kernel"].astype(dt)            # (ff_loc, d)
    b2 = lp["mlp_out"]["bias"].astype(dt)              # (d,) replicated
    return x + _tp_reduce(mid @ w2) + b2


def _moe_pattern(cfg: TransformerConfig):
    """Per-layer use_moe flags — delegates to the ONE schedule
    definition on the config (shared with the flax Transformer)."""
    return cfg.moe_pattern()


def _moe_groups(cfg: TransformerConfig, n: int) -> Tuple[int, int]:
    """(group size, group count) — the ONE group-partition definition
    (models.transformer.moe_group_partition), un-anchored: inside the
    pp shard_map the partition must depend only on (cfg, n) so ep
    stays a pure layout choice at pinned step-0 exactness (the GSPMD
    trainer's mesh-anchored variant would change the partition with
    the mesh shape; its parity suite re-baselines both worlds
    instead). The a2a layout therefore stays opt-in-by-group-size
    here: pick moe_group_size so the group count divides ep."""
    from sparktorch_tpu.models.transformer import moe_group_partition

    return moe_group_partition(cfg, n)


def pp_moe_group_size(cfg: TransformerConfig, n_tokens: int,
                      n_ep: int) -> Optional[int]:
    """The a2a grouping OPT-IN for MoE inside a pp schedule: the
    largest group size ``g <= cfg.moe_group_size`` that partitions
    ``n_tokens`` (one microbatch's tokens per dp/sp shard) into a
    group count divisible by ``n_ep`` — exactly the group-size choice
    the gpipe-ep dryrun config makes by hand, so the 'auto' dispatch
    (:func:`_moe_ffn_ep_dispatch`) takes the all-to-all layout instead
    of silently falling back to token replication. Returns None when
    no such size exists (the replicated fallback is then the only
    layout, and the caller should leave the config untouched). The
    pp group partition is deliberately un-anchored (see
    :func:`_moe_groups`), which is why the opt-in must come from the
    group SIZE rather than a mesh-derived partition."""
    if n_ep <= 1 or n_tokens <= 0:
        return None
    cap = max(1, int(cfg.moe_group_size))
    for g in range(min(cap, n_tokens), 0, -1):
        if n_tokens % g == 0 and (n_tokens // g) % n_ep == 0:
            return g
    return None


def pp_moe_opt_in_cfg(cfg: TransformerConfig, rows: int, seq: int,
                      dp: int, sp: int, ep: int,
                      n_micro: int) -> TransformerConfig:
    """Apply :func:`pp_moe_group_size` to a config about to build a
    pp step: returns ``cfg`` with ``moe_group_size`` replaced by the
    a2a opt-in when one exists for this (batch, mesh, n_micro)
    partition, or unchanged otherwise. The ONE definition both the
    tuner's measured candidate and the ``mesh='auto'`` winner build
    go through — the two must agree or the measured layout is not
    the one production pays for."""
    if cfg.n_experts <= 0 or ep <= 1:
        return cfg
    tokens = (rows // max(1, dp) // max(1, n_micro)) * (seq // max(1, sp))
    gs = pp_moe_group_size(cfg, tokens, ep)
    if gs is not None and gs != cfg.moe_group_size:
        return dataclasses.replace(cfg, moe_group_size=gs)
    return cfg


def build_pp_schedule_step(spec, mesh: Mesh,
                           schedule_meta, rows: int, seq: int,
                           tx: Optional[
                               optax.GradientTransformation] = None,
                           rng: Optional[jax.Array] = None,
                           sample_x=None):
    """Build a pipeline-scheduled step from a ``ModelSpec`` + a tuner
    schedule meta (``{"schedule": gpipe|1f1b|interleaved,
    "virtual_stages": V, "n_micro": M}``) — THE one build path shared
    by the tuner's measured candidate
    (:func:`sparktorch_tpu.parallel.tune.prepare_pipeline_candidate`)
    and the ``mesh='auto'`` winner
    (:func:`sparktorch_tpu.train.sharded._make_auto_pipeline_step`),
    so the measured layout and the production step cannot diverge.

    Validates the meta (schedule name, rows % (dp x n_micro)), picks
    the head from the module type, threads the MoE a2a group-size
    opt-in (:func:`pp_moe_opt_in_cfg`), restacks the spec's flax
    params into the pipeline layout (interleave-permuted for
    ``virtual_stages > 1``), places the state over ``mesh``, and
    returns ``(state, step, cfg_used, head)`` — no dispatch happens
    here, so callers own their compile accounting."""
    from sparktorch_tpu.models.transformer import CausalLM

    meta = dict(schedule_meta or {})
    if not meta:
        raise ValueError("pp>1 build requires a schedule meta")
    sched = str(meta.get("schedule"))
    if sched not in ("gpipe", "1f1b", "interleaved"):
        raise ValueError(f"unknown pipeline schedule {sched!r}")
    v_stages = int(meta.get("virtual_stages", 1))
    n_micro = int(meta["n_micro"])
    # "interleaved" is the search-space name; this trainer spells it
    # schedule='1f1b' + virtual_stages=V.
    pp_schedule = "1f1b" if sched in ("1f1b", "interleaved") else "gpipe"

    tx = tx or spec.make_optimizer()
    module = spec.make_module()
    cfg = getattr(module, "config", None)
    if cfg is None or not hasattr(cfg, "d_model"):
        raise ValueError(
            "pipeline schedules need a transformer ModelSpec "
            f"(got {type(module).__name__})"
        )
    head = "lm" if isinstance(module, CausalLM) else "classifier"
    sizes = dict(mesh.shape)
    dp = sizes[AXIS_DP]
    if rows % (dp * n_micro) != 0:
        raise ValueError(
            f"batch rows {rows} not divisible by dp({dp}) x "
            f"n_micro({n_micro})"
        )
    cfg = pp_moe_opt_in_cfg(cfg, rows, seq, dp,
                            sizes.get(AXIS_SP, 1),
                            sizes.get(AXIS_EP, 1), n_micro)
    if rng is None:
        rng = jax.random.key(0)
    if sample_x is None:
        sample_x = np.zeros((1, seq), np.int32)
    flax_params = dict(spec.init_params(
        rng, sample_x=np.asarray(sample_x)))["params"]
    pparams = pipeline_params_from_flax(flax_params, cfg)
    if v_stages > 1:
        pparams = apply_interleave_permutation(
            pparams, cfg, sizes[AXIS_PP], v_stages)
    state = place_pipeline_state(pparams, tx, mesh)
    step = make_pp_train_step(
        cfg, tx, mesh, n_micro=n_micro, head=head,
        schedule=pp_schedule, virtual_stages=v_stages,
    )
    return state, step, cfg, head


def _moe_route(cfg: TransformerConfig, mp, tokens, mask, cap: int):
    """Router + GShard capacity assignment for a block of routing
    groups — the exact routing math of
    :class:`models.transformer.MoEFFN`, factored so the replicated and
    all-to-all ep layouts share one definition (routing is per-group,
    so it is layout-independent). ``tokens``: (G, g, d). Returns
    ``(probs, oh, gates, disp, keep)`` with ``disp`` the
    (G, g, k, e, cap) choice-level dispatch plan."""
    e = cfg.n_experts
    k = max(1, min(cfg.moe_top_k, e))
    n_groups, g, _ = tokens.shape
    # Router in f32 (small matmul; numerics matter more than MXU).
    logits = (
        tokens.astype(jnp.float32) @ mp["router"]["kernel"]
        + mp["router"]["bias"]
    )                                            # (G, g, e)
    probs = jax.nn.softmax(logits, axis=-1)
    topk_p, topk_idx = jax.lax.top_k(probs, k)   # (G, g, k)
    if k == 1:
        gates = topk_p
    else:
        gates = topk_p / jnp.maximum(
            jnp.sum(topk_p, axis=-1, keepdims=True), 1e-9
        )
    oh = jax.nn.one_hot(topk_idx, e, dtype=jnp.int32)  # (G, g, k, e)
    if mask is not None:
        oh = oh * mask[:, :, None, None]
        gates = gates * mask[:, :, None]
    # Choice-major capacity priority (GShard): ALL first choices rank
    # before any second choice.
    oh_t = oh.transpose(0, 2, 1, 3).reshape(n_groups, k * g, e)
    pos = jnp.cumsum(oh_t, axis=1) * oh_t
    keep = (pos > 0) & (pos <= cap)
    slot = jnp.clip(pos - 1, 0, cap - 1)
    disp_flat = keep[..., None] & jax.nn.one_hot(slot, cap, dtype=bool)
    disp = disp_flat.reshape(n_groups, k, g, e, cap).transpose(0, 2, 1, 3, 4)
    return probs, oh, gates, disp, keep


def _moe_aux_counts(cfg: TransformerConfig, probs, oh, keep, mask):
    """Load-balance + observability sums over THIS block of groups:
    ``(term, dropped, routed)`` where ``term`` = sum over the block's
    groups of sum_e frac_e*mean_prob_e (the caller normalizes by the
    GLOBAL group count and applies moe_aux_weight * e)."""
    oh0 = oh[:, :, 0, :].astype(jnp.float32)
    if mask is not None:
        mf = mask.astype(jnp.float32)
        valid = jnp.maximum(jnp.sum(mf, axis=1), 1.0)
        frac = jnp.sum(oh0, axis=1) / valid[:, None]
        mean_prob = jnp.sum(probs * mf[:, :, None], axis=1) / valid[:, None]
    else:
        frac = jnp.mean(oh0, axis=1)
        mean_prob = jnp.mean(probs, axis=1)
    term = jnp.sum(frac * mean_prob)
    routed = jnp.sum(oh).astype(jnp.float32)
    kept = jnp.sum(keep.astype(jnp.float32))
    return term, routed - kept, routed


def _moe_ffn_ep(cfg: TransformerConfig, mp, h, token_w, n_ep: int):
    """Replicated-token expert-parallel MoE FFN inside the pp
    shard_map: tokens replicate across ep members (the batch shards
    over dp only), the router is replicated so every member computes
    identical routing, and each member applies only its local slice of
    experts — one psum over ``ep`` combines the partial outputs.
    Correct at any ep, but per-member routing work and activation
    bytes do NOT shrink with ep — :func:`_moe_ffn_ep_a2a` is the
    scaling layout; this one remains for group counts that don't
    divide by ep (and as the parity reference). Returns
    (out, aux_loss, dropped, routed) — the observables MoEFFN sows.

    ``mp`` is the LOCAL moe param subtree: expert leaves arrive
    pre-sliced to ``e_loc = n_experts/ep`` by shard_map; router params
    replicated."""
    import math

    dt = cfg.compute_dtype
    b, s, d = h.shape
    e = cfg.n_experts
    e_loc = e // n_ep
    k = max(1, min(cfg.moe_top_k, e))
    n = b * s
    g, n_groups = _moe_groups(cfg, n)
    tokens = h.reshape(n_groups, g, d)
    if n_ep > 1:
        # Identity forward / psum-over-ep backward: the ONLY consumer
        # of `tokens` is the expert path (router + dispatch), whose
        # per-member input-cotangents are partial (one expert slice
        # each) — _ep_enter completes them so upstream grads stay
        # ep-replicated.
        tokens = _ep_enter(tokens)
    cap = max(1, math.ceil(cfg.capacity_factor * g * k / e))
    mask = (token_w.reshape(n_groups, g) > 0) if token_w is not None else None

    probs, oh, gates, disp, keep = _moe_route(cfg, mp, tokens, mask, cap)
    dispatch = jnp.any(disp, axis=2).astype(dt)  # (G, g, e, cap)
    combine = jnp.einsum("gnk,gnkec->gnec", gates.astype(dt),
                         disp.astype(dt))        # (G, g, e, cap)
    # Local experts slice of the (replicated) dispatch/combine plans.
    if n_ep > 1:
        off = jax.lax.axis_index(AXIS_EP) * e_loc
        dispatch = jax.lax.dynamic_slice_in_dim(dispatch, off, e_loc, axis=2)
        combine = jax.lax.dynamic_slice_in_dim(combine, off, e_loc, axis=2)

    expert_in = jnp.einsum("gnec,gnd->gecd", dispatch, tokens.astype(dt))
    hmid = jnp.einsum("gecd,edf->gecf", expert_in, mp["moe_w_in"].astype(dt))
    hmid = nn.gelu(hmid + mp["moe_b_in"][None, :, None].astype(dt))
    expert_out = jnp.einsum("gecf,efd->gecd", hmid,
                            mp["moe_w_out"].astype(dt))
    expert_out = expert_out + mp["moe_b_out"][None, :, None].astype(dt)
    out = jnp.einsum("gnec,gecd->gnd", combine, expert_out)
    if n_ep > 1:
        # Each member combined only its experts' outputs; the sum over
        # ep members is the full gate-weighted combine (custom-vjp:
        # identity backward, so the output cotangent isn't re-summed).
        out = _ep_reduce(out)

    # Aux + drop counts from the (replicated) routing — already global
    # per (pp, dp) shard, no ep reduction.
    term, dropped, routed = _moe_aux_counts(cfg, probs, oh, keep, mask)
    aux = cfg.moe_aux_weight * e * term / n_groups
    if n_ep > 1:
        # The aux VALUE is replicated across ep (computed from the
        # replicated routing), but its router gradient is computed in
        # full on every member — while the task path contributes only
        # a per-member share. Scale the aux GRADIENT by 1/ep (value
        # unchanged) so the (dp, ep) psum of router grads is exact.
        aux = aux / n_ep + jax.lax.stop_gradient(aux * (1.0 - 1.0 / n_ep))
    return out.reshape(b, s, d), aux, dropped, routed


def _moe_ffn_ep_a2a(cfg: TransformerConfig, mp, h, token_w, n_ep: int):
    """GShard-style expert-parallel MoE FFN inside the pp shard_map:
    token blocks travel to their experts' owners over an explicit
    ``all_to_all`` (and back), so — unlike the replicated layout —
    per-member routing/dispatch work and activation bytes scale 1/ep.

    Layout (the explicit-collective twin of the sharding-constraint
    layout in ``models.transformer.MoEFFN``):

    1. each ep member takes its 1/ep block of the routing GROUPS
       (:func:`_ep_scatter`; groups route independently, so routing
       decisions are bit-identical to ep=1),
    2. routes only those groups and builds its (G_loc, e, cap)
       dispatch plan + (G_loc, e, cap, d) expert inputs,
    3. ``all_to_all``: expert blocks swap for group blocks — each
       member now holds (G, e_loc, cap, d), every group's capacity
       slots for ITS experts,
    4. local expert FFN, reverse ``all_to_all``, gate-weighted combine
       of its own groups,
    5. :func:`_ep_gather` restores the ep-replicated (b, s, d) layout
       the surrounding (attention/residual) stage math expects.

    Requires ``n_groups % ep == 0`` (the dispatcher falls back to the
    replicated layout otherwise). Same return contract as
    :func:`_moe_ffn_ep`; exactness against it is pinned by
    ``test_pp_ep_a2a_parity``."""
    import math

    dt = cfg.compute_dtype
    b, s, d = h.shape
    e = cfg.n_experts
    k = max(1, min(cfg.moe_top_k, e))
    n = b * s
    g, n_groups = _moe_groups(cfg, n)
    g_loc = n_groups // n_ep
    cap = max(1, math.ceil(cfg.capacity_factor * g * k / e))

    tokens = _ep_scatter(h.reshape(n_groups, g, d), g_loc)  # (G_loc, g, d)
    if token_w is not None:
        i = jax.lax.axis_index(AXIS_EP)
        mask = jax.lax.dynamic_slice_in_dim(
            token_w.reshape(n_groups, g) > 0, i * g_loc, g_loc, 0
        )
    else:
        mask = None

    probs, oh, gates, disp, keep = _moe_route(cfg, mp, tokens, mask, cap)
    dispatch = jnp.any(disp, axis=2).astype(dt)      # (G_loc, g, e, cap)
    combine = jnp.einsum("gnk,gnkec->gnec", gates.astype(dt),
                         disp.astype(dt))            # (G_loc, g, e, cap)

    expert_in = jnp.einsum("gnec,gnd->gecd", dispatch,
                           tokens.astype(dt))        # (G_loc, e, cap, d)
    expert_in = _a2a_ep(expert_in, 1, 0)             # (G, e_loc, cap, d)
    hmid = jnp.einsum("gecd,edf->gecf", expert_in, mp["moe_w_in"].astype(dt))
    hmid = nn.gelu(hmid + mp["moe_b_in"][None, :, None].astype(dt))
    expert_out = jnp.einsum("gecf,efd->gecd", hmid,
                            mp["moe_w_out"].astype(dt))
    expert_out = expert_out + mp["moe_b_out"][None, :, None].astype(dt)
    back = _a2a_ep(expert_out, 0, 1)                 # (G_loc, e, cap, d)
    out_loc = jnp.einsum("gnec,gecd->gnd", combine, back)  # (G_loc, g, d)
    out = _ep_gather(out_loc).reshape(b, s, d)

    # Per-member partial sums over its OWN groups; the aux value uses
    # _ep_reduce (psum forward, identity backward) so each member's
    # router gradient stays its true per-group share — the (dp, ep)
    # psum in the trainer's grad reduction completes it. Drop counts
    # are metrics (never differentiated): a plain psum globalizes them.
    term, dropped, routed = _moe_aux_counts(cfg, probs, oh, keep, mask)
    aux = cfg.moe_aux_weight * e * _ep_reduce(term) / n_groups
    dropped = jax.lax.psum(dropped, AXIS_EP)
    routed = jax.lax.psum(routed, AXIS_EP)
    return out, aux, dropped, routed


def _moe_ffn_ep_dispatch(cfg: TransformerConfig, mp, h, token_w, n_ep: int):
    """Pick the ep layout per ``cfg.moe_ep_dispatch`` ('a2a' /
    'replicate' / 'auto'; trace-time decision — shapes are static)."""
    mode = cfg.moe_ep_dispatch
    if mode not in ("auto", "a2a", "replicate"):
        raise ValueError(f"unknown moe_ep_dispatch {mode!r}")
    _, n_groups = _moe_groups(cfg, h.shape[0] * h.shape[1])
    divisible = n_groups % n_ep == 0
    if mode == "a2a" and not divisible:
        raise ValueError(
            f"moe_ep_dispatch='a2a' needs the routing group count "
            f"({n_groups}) divisible by ep={n_ep}; lower moe_group_size "
            "or use 'auto'"
        )
    if n_ep > 1 and divisible and mode in ("auto", "a2a"):
        return _moe_ffn_ep_a2a(cfg, mp, h, token_w, n_ep)
    return _moe_ffn_ep(cfg, mp, h, token_w, n_ep)


def interleave_stack_permutation(n_layers: int, S: int, V: int) -> np.ndarray:
    """Global layer order for the INTERLEAVED pipeline layout: virtual
    stage j = v*S + d (v-th chunk on device d) covers global layers
    [j*lps, (j+1)*lps), and the pp sharding splits the stacked layer
    dim into S contiguous device blocks — so device d's block must
    hold its V chunks in chunk order. Apply to the stacked tree before
    :func:`place_pipeline_state` (``a[perm]``); invert with
    ``np.argsort(perm)`` after training. V=1 is the identity."""
    if n_layers % (S * V) != 0:
        raise ValueError(
            f"n_layers={n_layers} not divisible by pp*virtual_stages="
            f"{S * V}"
        )
    lps = n_layers // (S * V)
    order = []
    for d in range(S):
        for v in range(V):
            j = v * S + d
            order.extend(range(j * lps, (j + 1) * lps))
    return np.asarray(order)


def apply_interleave_permutation(pparams, cfg: TransformerConfig,
                                 S: int, V: int, inverse: bool = False):
    """Permute the stacked layer trees into (``inverse=False``) or
    back out of (``inverse=True``) the interleaved layout. The dense
    and MoE stacks permute INDEPENDENTLY: with a per-chunk-uniform
    pattern (enforced by ``make_pp_train_step``) each chunk holds a
    fixed count of each kind, so each stack's chunk rows are
    contiguous and reorder with that stack's own interleave
    permutation."""
    pattern = _moe_pattern(cfg)
    out = dict(pparams)
    for key, count in (("layers", pattern.count(False)),
                       ("layers_moe", pattern.count(True))):
        if key in out and count:
            p = interleave_stack_permutation(count, S, V)
            if inverse:
                p = np.argsort(p)
            out[key] = jax.tree.map(lambda a, p=p: a[p], out[key])
    return out


def _interleaved_schedule(S: int, V: int, M: int):
    """Host-side static schedule for interleaved 1F1B on a global
    combined-tick clock. Microbatches advance in groups of S per chunk
    (the Megatron ordering), giving closed-form tick times:

      fwd  of stage j=v*S+d, microbatch m=g*S+r:
          t = g*V*S + v*S + r + d
      bwd (mirrored), offset D = V*S - 1:
          t = D + g*V*S + (V-1-v)*S + r + (S-1-d)

    Every consecutive virtual stage runs EXACTLY one tick later, so
    the single +1-ring ppermute per tick delivers each activation the
    tick it is consumed — no receive buffering. Total ticks
    T = V*M + V*S + S - 2 (V=1 recovers the plain 1F1B's M + 2S - 2);
    per tick each device does ONE chunk fwd + ONE chunk bwd (1/V of a
    full stage), so the warmup/drain bubble shrinks ~V-fold relative
    to plain 1F1B at equal per-tick width.

    Returns ``(T, fwd_v, fwd_m, bwd_v, bwd_m)`` with (T, S) int32
    tables, -1 marking an idle sub-tick."""
    if M % S != 0:
        raise ValueError(
            f"interleaved 1F1B needs n_micro ({M}) divisible by pp ({S})"
        )
    D = V * S - 1
    T = V * M + V * S + S - 2
    fwd_v = -np.ones((T, S), np.int32)
    fwd_m = -np.ones((T, S), np.int32)
    bwd_v = -np.ones((T, S), np.int32)
    bwd_m = -np.ones((T, S), np.int32)
    for d in range(S):
        for g in range(M // S):
            for v in range(V):
                for r in range(S):
                    m = g * S + r
                    tf = g * V * S + v * S + r + d
                    tb = D + g * V * S + (V - 1 - v) * S + r + (S - 1 - d)
                    assert fwd_v[tf, d] < 0 and bwd_v[tb, d] < 0, "collision"
                    fwd_v[tf, d] = v
                    fwd_m[tf, d] = m
                    bwd_v[tb, d] = v
                    bwd_m[tb, d] = m
    return T, fwd_v, fwd_m, bwd_v, bwd_m


def _interleaved_ring_slots(S: int, V: int, M: int, tables=None) -> int:
    """Smallest ring size RV such that slot ``m % RV`` is collision-
    free among in-flight microbatches of any one chunk (checked
    exactly against the schedule's [t_fwd, t_bwd] lifetimes).
    ``tables``: pass the already-computed ``_interleaved_schedule``
    result to avoid rebuilding it."""
    T, fwd_v, fwd_m, bwd_v, bwd_m = (
        tables if tables is not None else _interleaved_schedule(S, V, M)
    )
    # Lifetimes grouped by (device, chunk) — only same-chunk
    # microbatches can collide on a slot.
    groups: dict = {}
    for d in range(S):
        for t in range(T):
            if fwd_v[t, d] >= 0:
                groups.setdefault((d, int(fwd_v[t, d])), {})[
                    int(fwd_m[t, d])
                ] = [t, None]
            if bwd_v[t, d] >= 0:
                groups[(d, int(bwd_v[t, d]))][int(bwd_m[t, d])][1] = t
    for RV in range(1, 3 * S + 2):
        ok = True
        for life in groups.values():
            for m, (t0, t1) in life.items():
                # Only later microbatches sharing the slot can overlap.
                m2 = m + RV
                while ok and m2 in life:
                    u0, u1 = life[m2]
                    if not (t1 < u0 or u1 < t0):
                        ok = False
                    m2 += RV
                if not ok:
                    break
            if not ok:
                break
        if ok:
            return RV
    return M  # fallback: one slot per microbatch


def _stacked_layer_init(cfg, key, use_moe: bool, n: int):
    if cfg.attn_impl == "ring":
        # The attention impl never changes the param tree; the flax
        # ring branch would open its own shard_map island (needs an
        # ambient mesh) just to trace init — init as dense instead.
        cfg = dataclasses.replace(cfg, attn_impl="dense")
    layer = EncoderLayer(cfg, use_moe=use_moe)
    sample_h = jnp.zeros((1, cfg.max_len, cfg.d_model), cfg.compute_dtype)
    keys = jax.random.split(key, n)
    return jax.vmap(lambda k: layer.init(k, sample_h)["params"])(keys)


def _init_backbone(cfg: TransformerConfig, k_embed, k_pos, k_dense, k_moe):
    """Shared pipeline backbone init: embeddings, final norm, and the
    dense / MoE layer stacks (separate stacks — their trees differ;
    each pp-sharded on its leading layer dim)."""
    pattern = _moe_pattern(cfg)
    n_dense = pattern.count(False)
    n_moe = pattern.count(True)
    d = cfg.d_model
    params = {
        "tok_embed": jax.random.normal(k_embed, (cfg.vocab_size, d)) * 0.02,
        "pos_embed": jax.random.normal(k_pos, (cfg.max_len, d)) * 0.02,
        "ln_scale": jnp.ones((d,)),
        "ln_bias": jnp.zeros((d,)),
    }
    if n_dense:
        params["layers"] = _stacked_layer_init(cfg, k_dense, False, n_dense)
    if n_moe:
        params["layers_moe"] = _stacked_layer_init(cfg, k_moe, True, n_moe)
    return params


def init_pipeline_lm(cfg: TransformerConfig, key: jax.Array):
    """Host-side init of a causal LM laid out for pipelining: the
    encoder layers' params are STACKED on a leading (layer) dim — the
    dim the pp sharding splits — plus replicated embedding / final
    norm / LM head tensors."""
    cfg = dataclasses.replace(cfg, causal=True)
    k_embed, k_pos, k_head, k_dense, k_moe = jax.random.split(key, 5)
    d = cfg.d_model
    params = _init_backbone(cfg, k_embed, k_pos, k_dense, k_moe)
    params["head_w"] = jax.random.normal(k_head, (d, cfg.vocab_size)) * (
        1.0 / np.sqrt(d)
    )
    params["head_b"] = jnp.zeros((cfg.vocab_size,))
    return params


def init_pipeline_classifier(cfg: TransformerConfig, key: jax.Array):
    """Pipeline layout of the BERT-style ``SequenceClassifier``: same
    stacked layers + embedding, with a pooler (tanh) + classifier head
    instead of the LM head."""
    k_embed, k_pos, k_pool, k_cls, k_dense, k_moe = jax.random.split(key, 6)
    d = cfg.d_model
    params = _init_backbone(cfg, k_embed, k_pos, k_dense, k_moe)
    params["pool_w"] = jax.random.normal(k_pool, (d, d)) * (1.0 / np.sqrt(d))
    params["pool_b"] = jnp.zeros((d,))
    params["cls_w"] = jax.random.normal(k_cls, (d, cfg.n_classes)) * (
        1.0 / np.sqrt(d)
    )
    params["cls_b"] = jnp.zeros((cfg.n_classes,))
    return params


# Per-leaf tp sharding of the stacked layer tree, keyed by the dim the
# head/column slice lives on (after the leading layer-stack dim).
_TP_LAYER_DIMS = {
    ("attn", "qkv", "kernel"): 3,   # (L, d, 3, h, hd) -> heads
    ("attn", "qkv", "bias"): 2,     # (L, 3, h, hd)
    ("attn", "proj", "kernel"): 1,  # (L, h, hd, d)
    ("mlp_in", "kernel"): 2,        # (L, d, ff)
    ("mlp_in", "bias"): 1,          # (L, ff)
    ("mlp_out", "kernel"): 1,       # (L, ff, d)
}


def _layer_leaf_spec(path_names: Tuple[str, ...], ndim: int) -> P:
    """Spec for one stacked-layer leaf: pp on the stack dim, tp on the
    leaf's head/column dim when it has one."""
    for key, dim in _TP_LAYER_DIMS.items():
        if path_names[-len(key):] == key:
            parts = [AXIS_PP] + [None] * (ndim - 1)
            parts[dim] = AXIS_TP
            return P(*parts)
    return P(AXIS_PP)


_MOE_EXPERT_LEAVES = ("moe_w_in", "moe_b_in", "moe_w_out", "moe_b_out")


def _path_names(path) -> Tuple[str, ...]:
    """Decode a tree_util key path into plain name strings — the one
    place for the idiom, so the grad-reduction and norm-weighting
    rules keyed off these names stay consistent with the sharding
    specs."""
    return tuple(
        str(getattr(p, "key", getattr(p, "name", p))) for p in path
    )


def _moe_leaf_spec(path_names: Tuple[str, ...]) -> P:
    """Spec for one stacked MoE-layer leaf: pp on the stack dim, and —
    for the expert weight tensors, whose dim 1 is the experts dim —
    ep, so experts shard ACROSS chips within a pipeline stage. The
    router/ln/attn params replicate over ep (every ep member routes
    identically)."""
    if path_names[-1] in _MOE_EXPERT_LEAVES:
        return P(AXIS_PP, AXIS_EP)
    return P(AXIS_PP)


def _param_specs(params) -> Any:
    """Per-leaf PartitionSpecs: layer stacks split over pp on their
    leading (layer) dim and over tp on head/column dims; MoE layer
    stacks split over pp (stack dim) and ep (experts dim of the expert
    weights — tp is rejected with MoE); everything else replicated."""
    from jax.tree_util import tree_map_with_path

    def layers_spec(path, leaf):
        return _layer_leaf_spec(_path_names(path), np.ndim(leaf))

    def moe_spec(path, leaf):
        return _moe_leaf_spec(_path_names(path))

    return {
        k: (
            tree_map_with_path(layers_spec, v)
            if k == "layers"
            else tree_map_with_path(moe_spec, v)
            if k == "layers_moe"
            else jax.tree.map(lambda _: P(), v)
        )
        for k, v in params.items()
    }


def place_pipeline_state(params, tx, mesh: Mesh) -> PipelineState:
    """device_put params into their pipeline layout and init the
    optimizer on the placed arrays. EVERY leaf (incl. optimizer
    scalars and the step counter) gets an explicit mesh-wide
    sharding: eager optax init would otherwise leave scalar leaves on
    one device, and a checkpoint restored against those shardings
    could not feed the pp shard_map step."""
    specs = _param_specs(params)
    sh = jax.tree.map(
        lambda s: NamedSharding(mesh, s), specs,
        is_leaf=lambda x: isinstance(x, P),
    )
    params = jax.tree.map(jax.device_put, params, sh)
    opt_state = tx.init(params)
    opt_sh = jax.tree.map(
        lambda s: NamedSharding(mesh, s), _opt_specs(tx, opt_state, specs),
        is_leaf=lambda x: isinstance(x, P),
    )
    opt_state = jax.tree.map(jax.device_put, opt_state, opt_sh)
    return PipelineState(
        step=jax.device_put(jnp.zeros((), jnp.int32),
                            NamedSharding(mesh, P())),
        params=params,
        opt_state=opt_state,
    )


def make_pp_train_step(
    cfg: TransformerConfig,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    n_micro: int,
    head: str = "lm",
    mini_batch: Optional[int] = None,
    steps_per_call: int = 1,
    schedule: str = "gpipe",
    virtual_stages: int = 1,
) -> Callable[[PipelineState, DataBatch], Tuple[PipelineState, jax.Array]]:
    """Build the jitted pipelined train step over ``mesh`` (dp x pp x
    tp x sp x ep; other axes must be 1 for this trainer). sp > 1
    shards the sequence dim and requires ``attn_impl='ring'`` (the
    ring rides the same shard_map as the schedule). MoE stacks
    compose with sp when ``moe_group_size`` divides the per-shard
    sequence length (routing groups then tile inside sequence shards,
    keeping sp a pure layout choice), and with ep on the same mesh.

    ``head``: ``'lm'`` (next-token CE over the vocab, causal) or
    ``'classifier'`` (BERT-style pooler + class CE — the config-4
    workload, pipelined).

    ``mini_batch`` (per dp shard, like the DP trainer's): each step
    samples a contiguous random block of that many rows ON DEVICE
    (``utils.data.sample_minibatch``) and feeds it to the microbatch
    split — so it must divide into ``n_micro`` microbatches.
    ``steps_per_call=k`` scans k WHOLE schedules inside the one jitted
    call (fresh minibatch sample per step); with ``k == 1`` the step
    returns a scalar loss as before, otherwise ``(state, PpStepOut)``
    with per-step arrays."""
    if head not in ("lm", "classifier"):
        raise ValueError(f"unknown head {head!r}")
    if schedule not in ("gpipe", "1f1b"):
        raise ValueError(f"unknown pipeline schedule {schedule!r}")
    K = max(1, int(steps_per_call))
    if mini_batch is not None and mini_batch > 0:
        if mini_batch % n_micro != 0:
            raise ValueError(
                f"mini_batch={mini_batch} not divisible by "
                f"n_micro={n_micro}"
            )
    for ax in mesh.shape:
        if (ax not in (AXIS_DP, AXIS_PP, AXIS_TP, AXIS_EP, AXIS_SP)
                and mesh.shape[ax] != 1):
            raise ValueError(
                f"pipeline trainer supports dp x pp x tp x sp x ep only; "
                f"{ax}>1"
            )
    S = mesh.shape[AXIS_PP]
    T = mesh.shape[AXIS_TP]
    E = dict(mesh.shape).get(AXIS_EP, 1)
    SP = dict(mesh.shape).get(AXIS_SP, 1)
    if SP > 1 and cfg.attn_impl != "ring":
        raise ValueError(
            "mesh sp>1 shards the sequence: attention must be global "
            "over sp, so attn_impl must be 'ring' (dense/flash only see "
            "the local block)"
        )
    V = max(1, int(virtual_stages))
    if V > 1:
        # Interleaved 1F1B: V chunks per device, chunk-granular ticks
        # (the layer stack must be pre-permuted with
        # interleave_stack_permutation so device d's pp shard holds
        # stages {d, S+d, ...}).
        if schedule != "1f1b":
            raise ValueError(
                "virtual_stages>1 is the interleaved 1F1B schedule; "
                "set schedule='1f1b'"
            )
        if cfg.n_layers % (S * V) != 0:
            raise ValueError(
                f"n_layers={cfg.n_layers} not divisible by pp*virtual_"
                f"stages={S * V}"
            )
        if n_micro % S != 0:
            raise ValueError(
                f"interleaved 1F1B needs n_micro ({n_micro}) divisible "
                f"by pp ({S})"
            )
    if cfg.n_layers % max(1, S) != 0:
        raise ValueError(f"n_layers={cfg.n_layers} not divisible by pp={S}")
    if cfg.n_heads % max(1, T) != 0:
        raise ValueError(f"n_heads={cfg.n_heads} not divisible by tp={T}")
    if cfg.d_ff % max(1, T) != 0:
        raise ValueError(f"d_ff={cfg.d_ff} not divisible by tp={T}")
    # MoE composes when every stage sees the SAME dense/MoE layer
    # pattern (the two layer kinds live in separate pp-sharded
    # stacks); experts replicate within a stage — expert PARALLELISM
    # stays the GSPMD trainer's ep axis.
    pattern = _moe_pattern(cfg)
    has_moe = any(pattern)
    if V > 1 and has_moe:
        # Interleaved chunks are the schedule's unit: every one of the
        # S*V virtual stages must hold the same dense/MoE sequence so
        # (a) the per-kind stacks slice uniformly per chunk and (b)
        # the interleave permutation applies per stack.
        lps_c = cfg.n_layers // (S * V)
        chunk_patterns = [pattern[j * lps_c:(j + 1) * lps_c]
                          for j in range(S * V)]
        if any(cp != chunk_patterns[0] for cp in chunk_patterns):
            raise ValueError(
                f"interleaved 1F1B with MoE needs the dense/MoE "
                f"pattern {pattern} uniform across all pp*virtual_"
                f"stages={S * V} chunks; choose moe_every/n_layers "
                "accordingly"
            )
    if E > 1 and not has_moe:
        raise ValueError(
            "mesh ep>1 needs MoE layers (n_experts>0) — there are no "
            "experts to shard"
        )
    if has_moe:
        if T > 1:
            raise ValueError(
                "pp x tp with MoE layers is not supported; use tp=1 "
                "(experts shard over the ep axis instead)"
            )
        # sp>1 composes with MoE when moe_group_size tiles the
        # per-shard sequence (checked at trace time in moe_apply —
        # reached from every walk — where the shard's seq length is
        # known): routing groups then
        # sit INSIDE sequence-shard rows, so the sp>1 group partition
        # is exactly the sp=1 partition and sp stays a pure layout
        # choice. Each member's local aux is its per-shard share of
        # the global (sum over sp / SP) load-balance objective.
        if E > 1 and cfg.n_experts % E != 0:
            raise ValueError(
                f"n_experts={cfg.n_experts} not divisible by ep={E}"
            )
        lps = cfg.n_layers // max(1, S)
        stage_patterns = [pattern[s * lps:(s + 1) * lps] for s in range(S)]
        if any(sp != stage_patterns[0] for sp in stage_patterns):
            raise ValueError(
                f"MoE layer pattern {pattern} is not uniform across "
                f"pp={S} stages; choose moe_every/n_layers so every "
                "stage holds the same dense/MoE sequence"
            )
        stage_pattern = stage_patterns[0]
    if head == "lm":
        cfg = dataclasses.replace(cfg, causal=True)
    dt = cfg.compute_dtype

    layer_fwd = lambda lp, h: _layer_forward(cfg, lp, h)
    if cfg.remat:
        layer_fwd = jax.checkpoint(layer_fwd)

    def stage_fn(local_layers, h):
        def body(h, lp):
            return layer_fwd(lp, h), None

        h, _ = jax.lax.scan(body, h, local_layers)
        return h

    if has_moe:
        def moe_apply(lp, h, token_w):
            # Split the layer: the attention half is the SAME manual
            # math as the dense layers (so its ring branch works
            # under sp — a flax-module attention here would silently
            # fall back to block-local dense inside the Manual-axes
            # shard_map), and the expert FFN runs the layout picked by
            # moe_ep_dispatch (no collectives at ep=1; experts
            # pre-sliced over the ep axis by shard_map otherwise).
            if SP > 1 and h.shape[1] % max(1, cfg.moe_group_size):
                # Trace-time contract: groups must tile the per-shard
                # sequence rows so every group lives inside ONE sp
                # shard and both sp=1 and sp>1 pick g=moe_group_size —
                # the condition under which sp is a pure layout choice
                # for routing/capacity/aux (any other g silently
                # changes the group partition vs sp=1).
                raise ValueError(
                    f"pp x sp with MoE needs moe_group_size "
                    f"({cfg.moe_group_size}) dividing the per-shard "
                    f"sequence length ({h.shape[1]}); set "
                    "moe_group_size to a divisor of seq/sp"
                )
            x_mid = _attn_half(cfg, lp, h)
            h_ln = _ln(lp["ln_mlp"], x_mid, dt)
            moe_out, aux, dropped, routed = _moe_ffn_ep_dispatch(
                cfg, lp["moe"], h_ln, token_w, E
            )
            return x_mid + moe_out, aux, dropped, routed

        if cfg.remat:
            moe_apply = jax.checkpoint(moe_apply)

        def walk_moe(pattern_, layers, layers_moe, h, token_w):
            """Unrolled dense/MoE layer walk over ``pattern_``,
            indexing each kind's stacked rows in order — the ONE
            stage-body definition shared by the per-stage walk
            (stage_fn_moe) and the interleaved per-chunk walk
            (chunk_forward)."""
            aux = jnp.zeros((), jnp.float32)
            dropped = jnp.zeros((), jnp.float32)
            routed = jnp.zeros((), jnp.float32)
            jd = jm = 0
            for is_moe in pattern_:
                if is_moe:
                    lp = jax.tree.map(lambda a: a[jm], layers_moe)
                    h, a, dr, rt = moe_apply(lp, h, token_w)
                    aux = aux + a
                    dropped = dropped + dr
                    routed = routed + rt
                    jm += 1
                else:
                    lp = jax.tree.map(lambda a: a[jd], layers)
                    h = layer_fwd(lp, h)
                    jd += 1
            return h, aux, dropped, routed

        def stage_fn_moe(params, h, token_w):
            return walk_moe(stage_pattern, params.get("layers"),
                            params.get("layers_moe"), h, token_w)

    def embed(params, ids):
        s = ids.shape[1]
        if SP > 1:
            # ids hold this member's SEQUENCE shard: its positional
            # rows start at sp_index * s_local.
            off = jax.lax.axis_index(AXIS_SP) * s
            pe = jax.lax.dynamic_slice_in_dim(params["pos_embed"], off, s, 0)
        else:
            pe = params["pos_embed"][:s]
        h = params["tok_embed"][ids] + pe[None]
        return h.astype(dt)

    def head_loss(params, h, y, w):
        hf = _ln({"scale": params["ln_scale"], "bias": params["ln_bias"]},
                 h, jnp.float32)
        if head == "classifier":
            # Pooler in the model's compute dtype, classifier logits in
            # f32 — matching the flax SequenceClassifier exactly
            # (transformer.py: pooler Dense dtype=compute_dtype,
            # classifier Dense dtype=float32), so pp-trained params see
            # the same numerics the module applies at transform time.
            if SP > 1:
                # Mean-pool over the GLOBAL sequence: psum the local
                # sums (identity backward — each member's per-token
                # grads are its true share). The pooled stream is then
                # sp-REPLICATED, so the head params would see their
                # full gradient on every member: pre-scale their
                # cotangents by 1/sp so the trainer's sp psum is exact.
                pooled_in = _sp_reduce(hf.astype(dt).sum(1)) / (
                    h.shape[1] * SP
                )
                pool_w = _scale_grad(params["pool_w"], 1.0 / SP)
                pool_b = _scale_grad(params["pool_b"], 1.0 / SP)
                cls_w = _scale_grad(params["cls_w"], 1.0 / SP)
                cls_b = _scale_grad(params["cls_b"], 1.0 / SP)
            else:
                pooled_in = hf.astype(dt).mean(1)
                pool_w, pool_b = params["pool_w"], params["pool_b"]
                cls_w, cls_b = params["cls_w"], params["cls_b"]
            pooled = jnp.tanh(
                pooled_in @ pool_w.astype(dt) + pool_b.astype(dt)
            )
            logits = pooled.astype(jnp.float32) @ cls_w + cls_b
            per_ex = optax.softmax_cross_entropy_with_integer_labels(logits, y)
        else:
            logits = hf @ params["head_w"] + params["head_b"]
            per_tok = optax.softmax_cross_entropy_with_integer_labels(logits, y)
            if SP > 1:
                # Per-example mean over the GLOBAL sequence. Everything
                # upstream stays per-token (the head matmul runs on
                # local tokens), so all param grads remain honest
                # per-shard shares that the sp psum completes.
                per_ex = _sp_reduce(per_tok.sum(-1)) / (
                    per_tok.shape[-1] * SP
                )
            else:
                per_ex = per_tok.mean(-1)
        return jnp.sum(per_ex * w), jnp.sum(w)

    ring = [(i, (i + 1) % S) for i in range(S)]

    def schedule_loss(params, x, y, w):
        """The full GPipe schedule's global weighted-mean loss (plus
        the MoE aux term and drop fraction) — differentiated by
        local_step, called forward-only by the eval step."""
        stage = jax.lax.axis_index(AXIS_PP)
        b_local, s = x.shape
        if b_local % n_micro != 0:
            raise ValueError(
                f"local batch {b_local} not divisible by n_micro={n_micro}"
            )
        mb = b_local // n_micro
        micro_x = x.reshape(n_micro, mb, s)
        # lm targets are token-level (b, s); classifier labels (b,).
        micro_y = y.reshape((n_micro, mb) + y.shape[1:])
        micro_w = w.reshape(n_micro, mb)

        def pipeline_loss(params):
            def tick(carry, t):
                h_prev, num, den, aux, dropped, routed = carry
                inj = jnp.clip(t, 0, n_micro - 1)
                # Only stage 0 embeds and only the last stage (inside
                # its valid drain window) runs the vocab-sized head —
                # lax.cond skips the dead branch at runtime instead of
                # computing it everywhere and masking to zero (the
                # head matmul + its backward dominate for real vocabs).
                h_in = jax.lax.cond(
                    stage == 0,
                    lambda: embed(params, micro_x[inj]),
                    lambda: h_prev,
                )
                if has_moe:
                    # The microbatch THIS stage processes at tick t was
                    # injected at t - stage; bubble ticks (no valid
                    # microbatch) get all-zero token weights so their
                    # garbage activations never touch routing, capacity
                    # or the aux loss.
                    m_in = t - stage
                    mi_in = jnp.clip(m_in, 0, n_micro - 1)
                    valid_in = ((m_in >= 0) & (m_in < n_micro)).astype(
                        micro_w.dtype
                    )
                    tw = jnp.broadcast_to(
                        (micro_w[mi_in] * valid_in)[:, None], (mb, s)
                    )
                    h_out, aux_t, dr_t, rt_t = stage_fn_moe(params, h_in, tw)
                    aux = aux + aux_t
                    dropped = dropped + dr_t
                    routed = routed + rt_t
                else:
                    h_out = stage_fn(params["layers"], h_in)
                m = t - (S - 1)
                mi = jnp.clip(m, 0, n_micro - 1)
                use = (m >= 0) & (m < n_micro) & (stage == S - 1)
                n_, d_ = jax.lax.cond(
                    use,
                    lambda: head_loss(params, h_out, micro_y[mi], micro_w[mi]),
                    lambda: (jnp.zeros(()), jnp.zeros(())),
                )
                num = num + n_
                den = den + d_
                h_next = jax.lax.ppermute(h_out, AXIS_PP, ring)
                return (h_next, num, den, aux, dropped, routed), None

            init_h = jnp.zeros((mb, s, cfg.d_model), dt)
            zero = jnp.zeros(())
            (_, num, den, aux, dropped, routed), _ = jax.lax.scan(
                tick,
                (init_h, zero, zero, zero, zero, zero),
                jnp.arange(n_micro + S - 1),
            )
            num_g = jax.lax.psum(num, (AXIS_PP, AXIS_DP))
            den_g = jax.lax.psum(den, (AXIS_PP, AXIS_DP))
            task = num_g / jnp.maximum(den_g, 1.0)
            loss = task
            examples = den_g
            if has_moe:
                # Sum over stages/layers (psum pp — stages hold
                # disjoint MoE layers), mean over microbatches and dp
                # shards: the pipelined analog of the GSPMD trainer's
                # batch-mean sown aux. With sp>1 each member's local
                # aux covers its DISJOINT sequence-shard groups:
                # _sp_reduce (psum fwd / identity bwd) globalizes the
                # value while each member's backward keeps its honest
                # per-shard share (completed by the trainer's sp grad
                # psum), and /SP converts the sp-sum of local group
                # means into the global group mean.
                sp_axes = (AXIS_SP,) if SP > 1 else ()
                aux_g = jax.lax.psum(
                    _sp_reduce(aux) if SP > 1 else aux,
                    (AXIS_PP, AXIS_DP),
                )
                dp_n = _axis_size(AXIS_DP)
                loss = loss + aux_g / (n_micro * dp_n * SP)
                dropped_g = jax.lax.psum(
                    dropped, (AXIS_PP, AXIS_DP) + sp_axes
                )
                routed_g = jax.lax.psum(
                    routed, (AXIS_PP, AXIS_DP) + sp_axes
                )
                drop_fraction = dropped_g / jnp.maximum(routed_g, 1.0)
            else:
                drop_fraction = jnp.zeros(())
            # aux triple: (drop_fraction, task-only loss, examples) —
            # the eval path reports the task loss (the DP eval
            # excludes sown aux objectives from the validation signal
            # too); examples is the global weighted row count actually
            # trained on this step (== mini_batch rows when sampling).
            return loss, (drop_fraction, task, examples)

        return pipeline_loss(params)

    def one_f_one_b_grads(params, x, y, w):
        """1F1B schedule with a MANUAL backward: loss + gradients of
        the same math as ``schedule_loss`` (exactness-tested), with
        activation memory O(pp) instead of the O(n_micro) that
        autodiff-through-the-GPipe-scan stores.

        Combined-tick form: T = M + 2(S-1) ticks; at tick t stage s
        forwards microbatch ``t - s`` and backwards microbatch
        ``t - 2(S-1) + s`` (the last stage backwards a microbatch the
        same tick it forwards it). Each backward re-runs the stage
        forward under ``jax.vjp`` — residuals live only within the
        tick — so only the stage INPUTS of in-flight microbatches are
        stored, in a ring of ``2S-1`` slots. FLOPs match remat-GPipe
        (1 forward + recompute-backward per microbatch per stage);
        ticks are (M+2S-2) vs GPipe's (M+S-1) fused fwd+bwd ticks.

        Gradients accumulate for the SUM of weighted losses (num) and
        are scaled by the global weight den afterwards (den is
        params-independent), exactly reproducing num_g/max(den_g, 1).

        MoE stacks compose: each valid tick processes a REAL
        microbatch (bubbles are cond-skipped, so no zero-token-weight
        masking is needed, unlike the GPipe scan), the sown aux loss
        and drop counts accumulate in the forward sub-ticks, and the
        backward seeds the aux output with ``den_safe/(n_micro*dp)``
        so ONE pullback covers both the task path (later divided by
        den) and the aux path (whose GPipe weight is 1/(n_micro*dp))
        — den is params-independent and computable up front.
        """
        stage = jax.lax.axis_index(AXIS_PP)
        b_local, s_len = x.shape
        if b_local % n_micro != 0:
            raise ValueError(
                f"local batch {b_local} not divisible by n_micro={n_micro}"
            )
        mb = b_local // n_micro
        micro_x = x.reshape(n_micro, mb, s_len)
        micro_y = y.reshape((n_micro, mb) + y.shape[1:])
        micro_w = w.reshape(n_micro, mb)
        M = n_micro
        R = 2 * S - 1  # ring capacity >= max in-flight microbatches
        fwd_ring = [(i, (i + 1) % S) for i in range(S)]
        bwd_ring = [(i, (i - 1) % S) for i in range(S)]

        # den is the global weight sum — schedule-independent (w is
        # replicated across pp), so the aux seed below can use it.
        den_g = jax.lax.psum(jnp.sum(w), AXIS_DP)
        den_safe = jnp.maximum(den_g, 1.0)
        dp_n = _axis_size(AXIS_DP)
        # With sp>1 each member's local aux is a per-shard share of
        # the global aux = (sum over sp of local) / SP, so its
        # gradient weight carries an extra 1/SP.
        aux_seed = den_safe / (n_micro * dp_n * SP)

        def stage_out(p, h_in, tw):
            """(h_out, aux, dropped, routed) — zeros for dense."""
            if has_moe:
                return stage_fn_moe(p, h_in, tw)
            z = jnp.zeros(())
            return stage_fn(p["layers"], h_in), z, z, z

        def tick_outs(p, h_in, tw, mi):
            """Stage forward + (last-stage-only) head num, as ONE
            differentiable function — the sp>1 tick path, where the
            stage body contains ring-attention ppermutes that must
            execute UNCONDITIONALLY: a collective inside a lax.cond
            whose predicate varies over pp deadlocks/miscomputes (the
            sp members of a skipping stage never enter the exchange).
            Masking moves to the VJP seeds instead of branch choice.
            Returns the MoE drop metrics too — the forward sub-tick
            accumulates them (validity-masked); the backward vjp runs
            over the first three outputs only."""
            h_out, aux, dr_, rt_ = stage_out(p, h_in, tw)
            num = jax.lax.cond(
                stage == S - 1,
                lambda: head_loss(p, h_out, micro_y[mi], micro_w[mi])[0],
                lambda: jnp.zeros(()),
            )
            return h_out, num, aux, dr_, rt_

        def last_outs(p, h_in, yy, ww, tw):
            """(num, aux) of the last stage — the two differentiated
            outputs; den/drop-counts are params-independent."""
            h_out, aux, _, _ = stage_out(p, h_in, tw)
            num, _ = head_loss(p, h_out, yy, ww)
            return num, aux

        def mid_outs(p, h_in, tw):
            h_out, aux, _, _ = stage_out(p, h_in, tw)
            return h_out, aux

        def tw_of(ww):
            return jnp.broadcast_to(ww[:, None], (mb, s_len))

        zero_grads = jax.tree.map(jnp.zeros_like, params)

        def tick(carry, t):
            ring, fwd_ch, bwd_ch, grads, num, aux, dr, rt = carry

            # ---- forward sub-tick: microbatch t - stage ----
            m_f = t - stage
            fwd_valid = (m_f >= 0) & (m_f < M)
            mi_f = jnp.clip(m_f, 0, M - 1)

            def do_fwd():
                h_in = jax.lax.cond(
                    stage == 0,
                    lambda: embed(params, micro_x[mi_f]),
                    lambda: fwd_ch,
                )
                h_out, a_, dr_, rt_ = stage_out(params, h_in,
                                                tw_of(micro_w[mi_f]))
                n_ = jax.lax.cond(
                    stage == S - 1,
                    lambda: head_loss(params, h_out,
                                      micro_y[mi_f], micro_w[mi_f])[0],
                    lambda: jnp.zeros(()),
                )
                return h_in, h_out, n_, a_, dr_, rt_

            def skip_fwd():
                z = jnp.zeros((mb, s_len, cfg.d_model), dt)
                zs = jnp.zeros(())
                return z, z, zs, zs, zs, zs

            h_in, h_out, n_, a_, dr_, rt_ = jax.lax.cond(
                fwd_valid, do_fwd, skip_fwd
            )
            num = num + n_
            aux = aux + a_
            dr = dr + dr_
            rt = rt + rt_
            ring = jnp.where(
                fwd_valid,
                jax.lax.dynamic_update_slice(
                    ring, h_in[None], (mi_f % R, 0, 0, 0)
                ),
                ring,
            )

            # ---- backward sub-tick: microbatch t - 2(S-1) + stage ----
            m_b = t - 2 * (S - 1) + stage
            bwd_valid = (m_b >= 0) & (m_b < M)
            mi_b = jnp.clip(m_b, 0, M - 1)

            def do_bwd():
                h_saved = jax.lax.dynamic_index_in_dim(
                    ring, mi_b % R, axis=0, keepdims=False
                )
                tw_b = tw_of(micro_w[mi_b])

                def bwd_last():
                    _, pull = jax.vjp(
                        lambda p, h: last_outs(p, h, micro_y[mi_b],
                                               micro_w[mi_b], tw_b),
                        params, h_saved,
                    )
                    # Seeds: d(num)=1; aux pre-scaled by den_safe so
                    # the final /den_safe nets the GPipe aux weight.
                    return pull((jnp.ones(()), aux_seed))

                def bwd_mid():
                    _, pull = jax.vjp(
                        lambda p, h: mid_outs(p, h, tw_b),
                        params, h_saved,
                    )
                    return pull((bwd_ch, aux_seed))

                ct_params, ct_h = jax.lax.cond(
                    stage == S - 1, bwd_last, bwd_mid
                )
                # Stage 0 folds its input cotangent into the embedding
                # tables (its "previous stage").
                def embed_grads():
                    _, pull = jax.vjp(
                        lambda p: embed(p, micro_x[mi_b]), params
                    )
                    return pull(ct_h)[0]

                ct_params = jax.lax.cond(
                    stage == 0,
                    lambda: jax.tree.map(jnp.add, ct_params,
                                         embed_grads()),
                    lambda: ct_params,
                )
                return ct_params, ct_h

            def skip_bwd():
                return zero_grads, jnp.zeros((mb, s_len, cfg.d_model), dt)

            ct_params, ct_h = jax.lax.cond(bwd_valid, do_bwd, skip_bwd)
            grads = jax.tree.map(jnp.add, grads, ct_params)

            fwd_next = jax.lax.ppermute(h_out, AXIS_PP, fwd_ring)
            bwd_next = jax.lax.ppermute(ct_h, AXIS_PP, bwd_ring)
            return (ring, fwd_next, bwd_next, grads, num, aux, dr, rt), None

        def tick_masked(carry, t):
            """The sp>1 tick: identical math to ``tick``, but the stage
            body and ONE unified vjp run UNCONDITIONALLY every tick,
            with validity masking the accumulators and the vjp seeds
            instead of choosing a lax.cond branch. Required because the
            stage body contains ring-attention ppermutes over sp and a
            collective inside a cond whose predicate varies over pp
            deadlocks/miscomputes (the sp members of a skipping stage
            never enter the exchange — reproduced on the CPU backend).
            Costs bubble-tick compute, exactly like the GPipe scan."""
            ring, fwd_ch, bwd_ch, grads, num, aux, dr, rt = carry

            # ---- forward sub-tick: microbatch t - stage ----
            m_f = t - stage
            fwd_valid = (m_f >= 0) & (m_f < M)
            mi_f = jnp.clip(m_f, 0, M - 1)
            fv = fwd_valid.astype(jnp.float32)

            # embed has no collectives, so the stage-0 cond is safe
            # (unlike the stage body below, which must run everywhere).
            h_in = jax.lax.cond(
                stage == 0,
                lambda: embed(params, micro_x[mi_f]),
                lambda: fwd_ch,
            )
            h_out, n_, a_, dr_, rt_ = tick_outs(
                params, h_in, tw_of(micro_w[mi_f]), mi_f
            )
            num = num + fv * n_
            aux = aux + fv * a_
            # Bubble ticks route a REAL microbatch's token weights
            # over garbage activations (the body must run for its
            # collectives): validity-mask the drop metrics here, where
            # the GPipe scan masks via zeroed token weights instead.
            dr = dr + fv * dr_
            rt = rt + fv * rt_
            ring = jnp.where(
                fwd_valid,
                jax.lax.dynamic_update_slice(
                    ring, h_in[None], (mi_f % R, 0, 0, 0)
                ),
                ring,
            )

            # ---- backward sub-tick: microbatch t - 2(S-1) + stage ----
            m_b = t - 2 * (S - 1) + stage
            bwd_valid = (m_b >= 0) & (m_b < M)
            mi_b = jnp.clip(m_b, 0, M - 1)
            h_saved = jax.lax.dynamic_index_in_dim(
                ring, mi_b % R, axis=0, keepdims=False
            )
            tw_b = tw_of(micro_w[mi_b])
            _, pull = jax.vjp(
                lambda p, h: tick_outs(p, h, tw_b, mi_b)[:3],
                params, h_saved,
            )
            # Seeds do the masking (pullbacks are linear, so zero seeds
            # yield zero cotangents): the last stage's h_out cotangent
            # comes only through its own head term; mid stages seed
            # h_out with the ct arriving on the backward ring. The num
            # seed is harmless on mid stages (their num branch is the
            # zero function).
            bv = bwd_valid.astype(jnp.float32)
            seed_h = (
                jnp.where(bwd_valid & (stage != S - 1), 1.0, 0.0)
                .astype(dt) * bwd_ch
            )
            ct_params, ct_h = pull((seed_h, bv, bv * aux_seed))

            def embed_grads():
                _, epull = jax.vjp(
                    lambda p: embed(p, micro_x[mi_b]), params
                )
                return epull(ct_h)[0]

            # embed's vjp has no collectives, so this cond is safe.
            ct_params = jax.lax.cond(
                stage == 0,
                lambda: jax.tree.map(jnp.add, ct_params, embed_grads()),
                lambda: ct_params,
            )
            grads = jax.tree.map(jnp.add, grads, ct_params)

            fwd_next = jax.lax.ppermute(h_out, AXIS_PP, fwd_ring)
            bwd_next = jax.lax.ppermute(ct_h, AXIS_PP, bwd_ring)
            return (ring, fwd_next, bwd_next, grads, num, aux, dr, rt), None

        init = (
            jnp.zeros((R, mb, s_len, cfg.d_model), dt),
            jnp.zeros((mb, s_len, cfg.d_model), dt),
            jnp.zeros((mb, s_len, cfg.d_model), dt),
            zero_grads,
            jnp.zeros(()), jnp.zeros(()), jnp.zeros(()), jnp.zeros(()),
        )
        (_, _, _, grads, num, aux, dr, rt), _ = jax.lax.scan(
            tick_masked if SP > 1 else tick, init,
            jnp.arange(M + 2 * (S - 1))
        )
        num_g = jax.lax.psum(num, (AXIS_PP, AXIS_DP))
        loss = num_g / den_safe
        if has_moe:
            # Same accounting as the GPipe schedule_loss: stages hold
            # disjoint MoE layers (psum over pp), mean over
            # microbatches and dp shards; sp members hold disjoint
            # sequence-shard groups (sum over sp / SP).
            sp_axes = (AXIS_SP,) if SP > 1 else ()
            aux_g = jax.lax.psum(aux, (AXIS_PP, AXIS_DP) + sp_axes)
            loss = loss + aux_g / (n_micro * dp_n * SP)
            dr_g = jax.lax.psum(dr, (AXIS_PP, AXIS_DP) + sp_axes)
            rt_g = jax.lax.psum(rt, (AXIS_PP, AXIS_DP) + sp_axes)
            drop_fraction = dr_g / jnp.maximum(rt_g, 1.0)
        else:
            drop_fraction = jnp.zeros(())
        grads = jax.tree.map(lambda g: g / den_safe, grads)
        return loss, den_g, grads, drop_fraction

    if V > 1:
        T_ticks, _fv, _fm, _bv, _bm = _interleaved_schedule(S, V, n_micro)
        RV = _interleaved_ring_slots(
            S, V, n_micro, tables=(T_ticks, _fv, _fm, _bv, _bm)
        )
        fv_tab, fm_tab = jnp.asarray(_fv), jnp.asarray(_fm)
        bv_tab, bm_tab = jnp.asarray(_bv), jnp.asarray(_bm)
        lps_i = cfg.n_layers // (S * V)
        if has_moe:
            chunk_pattern = pattern[:lps_i]
            nd_c = chunk_pattern.count(False)
            nm_c = chunk_pattern.count(True)

        def chunk_params(p, v):
            """Device-local chunk v's layer rows. The dynamic slice
            transposes to a dynamic-update into zeros, so each
            backward lands its gradient on the right chunk rows. With
            MoE, each kind's stack slices by its own per-chunk count
            (the per-chunk-uniform pattern makes chunk rows
            contiguous in both stacks)."""
            if not has_moe:
                return jax.tree.map(
                    lambda a: jax.lax.dynamic_slice_in_dim(
                        a, v * lps_i, lps_i, 0
                    ),
                    p["layers"],
                )
            cp = {}
            if nd_c:
                cp["layers"] = jax.tree.map(
                    lambda a: jax.lax.dynamic_slice_in_dim(
                        a, v * nd_c, nd_c, 0
                    ),
                    p["layers"],
                )
            if nm_c:
                cp["layers_moe"] = jax.tree.map(
                    lambda a: jax.lax.dynamic_slice_in_dim(
                        a, v * nm_c, nm_c, 0
                    ),
                    p["layers_moe"],
                )
            return cp

        def chunk_forward(p, v, h, tw):
            """One chunk's stage walk — the interleaved twin of
            stage_fn/stage_fn_moe, shared by the train ticks and the
            forward-only eval. Returns (h, aux, dropped, routed);
            dense chunks return zero observables."""
            cp = chunk_params(p, v)
            if not has_moe:
                z = jnp.zeros((), jnp.float32)
                return stage_fn(cp, h), z, z, z
            return walk_moe(chunk_pattern, cp.get("layers"),
                            cp.get("layers_moe"), h, tw)

    def interleaved_grads(params, x, y, w):
        """Interleaved (virtual-stage) 1F1B: each device owns V chunks
        of lps = L/(S*V) layers (virtual stage j = v*S + d), and each
        combined tick runs ONE chunk forward + ONE chunk backward per
        the static ``_interleaved_schedule`` tables — 1/V of a plain
        1F1B tick's width, so the warmup/drain bubble shrinks ~V-fold:
        T = V*M + V*S + S - 2 chunk-ticks of (1 fwd + 1 recompute-bwd)
        chunk vs plain 1F1B's (M + 2S - 2) ticks of V-chunk width.
        Stage inputs persist in a (V, RV) ring (RV from the schedule's
        exact in-flight lifetimes): activation memory stays O(V*S),
        independent of M. Same gradient math as the other schedules
        (exactness-tested); the layer stack must be in the
        ``interleave_stack_permutation`` order."""
        stage = jax.lax.axis_index(AXIS_PP)
        b_local, s_len = x.shape
        if b_local % n_micro != 0:
            raise ValueError(
                f"local batch {b_local} not divisible by n_micro={n_micro}"
            )
        mb = b_local // n_micro
        micro_x = x.reshape(n_micro, mb, s_len)
        micro_y = y.reshape((n_micro, mb) + y.shape[1:])
        micro_w = w.reshape(n_micro, mb)
        M = n_micro
        fwd_ring = [(i, (i + 1) % S) for i in range(S)]
        bwd_ring = [(i, (i - 1) % S) for i in range(S)]
        dp_n = _axis_size(AXIS_DP)
        if has_moe:
            # den BEFORE the scan, like plain 1F1B: the aux seeds
            # consume it, which both weights the aux gradient
            # correctly (net 1/(n_micro*dp*SP) after the final
            # /den_safe) and — as a side effect — serializes the dp
            # psum against the scan's collectives (see the dense-path
            # barrier note below).
            den_pre = jax.lax.psum(jnp.sum(w), AXIS_DP)
            den_pre_safe = jnp.maximum(den_pre, 1.0)
            aux_seed = den_pre_safe / (n_micro * dp_n * SP)
        else:
            aux_seed = jnp.zeros(())

        def tw_of(mi):
            return (jnp.broadcast_to(micro_w[mi][:, None], (mb, s_len))
                    if has_moe else None)

        def chunk_outs(p, h_in, v, mi):
            """One chunk's forward + (final-virtual-stage-only) head
            num + MoE observables — the differentiable unit of the
            interleaved tick (the per-tick vjp runs over the first
            THREE outputs; drop counts are metrics only)."""
            h_out, aux, dr_, rt_ = chunk_forward(p, v, h_in, tw_of(mi))
            num = jax.lax.cond(
                (v == V - 1) & (stage == S - 1),
                lambda: head_loss(p, h_out, micro_y[mi], micro_w[mi])[0],
                lambda: jnp.zeros(()),
            )
            return h_out, num, aux, dr_, rt_

        zero_grads = jax.tree.map(jnp.zeros_like, params)

        def tick(carry, t):
            ring, fwd_ch, bwd_ch, grads, num, aux, dr, rt = carry

            vf = fv_tab[t, stage]
            mf = fm_tab[t, stage]
            fwd_valid = vf >= 0
            vf_c = jnp.clip(vf, 0, V - 1)
            mf_c = jnp.clip(mf, 0, M - 1)

            def do_fwd():
                h_in = jax.lax.cond(
                    (vf_c == 0) & (stage == 0),
                    lambda: embed(params, micro_x[mf_c]),
                    lambda: fwd_ch,
                )
                h_out, n_, a_, dr_, rt_ = chunk_outs(params, h_in,
                                                     vf_c, mf_c)
                return h_in, h_out, n_, a_, dr_, rt_

            def skip_fwd():
                z = jnp.zeros((mb, s_len, cfg.d_model), dt)
                zs = jnp.zeros(())
                return z, z, zs, zs, zs, zs

            h_in, h_out, n_, a_, dr_, rt_ = jax.lax.cond(
                fwd_valid, do_fwd, skip_fwd
            )
            num = num + n_
            aux = aux + a_
            dr = dr + dr_
            rt = rt + rt_
            ring = jnp.where(
                fwd_valid,
                jax.lax.dynamic_update_slice(
                    ring, h_in[None, None], (vf_c, mf_c % RV, 0, 0, 0)
                ),
                ring,
            )

            vb = bv_tab[t, stage]
            mb_i = bm_tab[t, stage]
            bwd_valid = vb >= 0
            vb_c = jnp.clip(vb, 0, V - 1)
            mb_c = jnp.clip(mb_i, 0, M - 1)

            def do_bwd():
                h_saved = jax.lax.dynamic_slice(
                    ring, (vb_c, mb_c % RV, 0, 0, 0),
                    (1, 1, mb, s_len, cfg.d_model),
                )[0, 0]
                is_last = (vb_c == V - 1) & (stage == S - 1)
                _, pull = jax.vjp(
                    lambda p, h: chunk_outs(p, h, vb_c, mb_c)[:3],
                    params, h_saved,
                )
                # Last virtual stage: h_out ct comes only through its
                # own head term; elsewhere seed with the backward-ring
                # ct (the num seed is harmless off the last stage —
                # that branch is the zero function there). The aux
                # seed covers the MoE load-balance path (zero for
                # dense chunks).
                seed_h = jnp.where(is_last, 0.0, 1.0).astype(dt) * bwd_ch
                ct_params, ct_h = pull((seed_h, jnp.ones(()), aux_seed))

                def embed_grads():
                    _, epull = jax.vjp(
                        lambda p: embed(p, micro_x[mb_c]), params
                    )
                    return epull(ct_h)[0]

                ct_params = jax.lax.cond(
                    (vb_c == 0) & (stage == 0),
                    lambda: jax.tree.map(jnp.add, ct_params,
                                         embed_grads()),
                    lambda: ct_params,
                )
                return ct_params, ct_h

            def skip_bwd():
                return zero_grads, jnp.zeros((mb, s_len, cfg.d_model), dt)

            ct_params, ct_h = jax.lax.cond(bwd_valid, do_bwd, skip_bwd)
            grads = jax.tree.map(jnp.add, grads, ct_params)

            fwd_next = jax.lax.ppermute(h_out, AXIS_PP, fwd_ring)
            bwd_next = jax.lax.ppermute(ct_h, AXIS_PP, bwd_ring)
            return (ring, fwd_next, bwd_next, grads, num, aux, dr, rt), None

        def tick_masked(carry, t):
            """The sp>1 interleaved tick: same discipline as the plain
            1F1B ``tick_masked`` — the chunk body (whose ring
            attention ppermutes over sp must execute on EVERY tick;
            a collective under a pp-varying lax.cond deadlocks or
            miscomputes) and one unified per-tick vjp run
            unconditionally, with validity masking the accumulators
            and the vjp seeds. chunk_outs' inner head cond is safe:
            its predicate (vf==V-1 & stage==S-1) is uniform across sp
            members, and invalid ticks clip vf to 0 != V-1 (V>=2), so
            the head never fires on garbage."""
            ring, fwd_ch, bwd_ch, grads, num, aux, dr, rt = carry

            vf = fv_tab[t, stage]
            mf = fm_tab[t, stage]
            fwd_valid = vf >= 0
            vf_c = jnp.clip(vf, 0, V - 1)
            mf_c = jnp.clip(mf, 0, M - 1)
            fv = fwd_valid.astype(jnp.float32)

            # embed has no collectives: the cond is safe (and on
            # invalid ticks h_in is garbage that nothing consumes —
            # the ring only stores it under fwd_valid).
            h_in = jax.lax.cond(
                (vf_c == 0) & (stage == 0),
                lambda: embed(params, micro_x[mf_c]),
                lambda: fwd_ch,
            )
            h_out, n_, a_, dr_, rt_ = chunk_outs(params, h_in, vf_c, mf_c)
            num = num + fv * n_
            # Bubble ticks route real token weights over garbage
            # activations (the body must run for its collectives):
            # validity-mask the MoE observables here.
            aux = aux + fv * a_
            dr = dr + fv * dr_
            rt = rt + fv * rt_
            ring = jnp.where(
                fwd_valid,
                jax.lax.dynamic_update_slice(
                    ring, h_in[None, None], (vf_c, mf_c % RV, 0, 0, 0)
                ),
                ring,
            )

            vb = bv_tab[t, stage]
            mb_i = bm_tab[t, stage]
            bwd_valid = vb >= 0
            vb_c = jnp.clip(vb, 0, V - 1)
            mb_c = jnp.clip(mb_i, 0, M - 1)
            h_saved = jax.lax.dynamic_slice(
                ring, (vb_c, mb_c % RV, 0, 0, 0),
                (1, 1, mb, s_len, cfg.d_model),
            )[0, 0]
            is_last = (vb_c == V - 1) & (stage == S - 1)
            _, pull = jax.vjp(
                lambda p, h: chunk_outs(p, h, vb_c, mb_c)[:3],
                params, h_saved,
            )
            bv = bwd_valid.astype(jnp.float32)
            seed_h = (
                jnp.where(bwd_valid & ~is_last, 1.0, 0.0).astype(dt)
                * bwd_ch
            )
            ct_params, ct_h = pull((seed_h, bv, bv * aux_seed))

            def embed_grads():
                _, epull = jax.vjp(
                    lambda p: embed(p, micro_x[mb_c]), params
                )
                return epull(ct_h)[0]

            ct_params = jax.lax.cond(
                (vb_c == 0) & (stage == 0),
                lambda: jax.tree.map(jnp.add, ct_params, embed_grads()),
                lambda: ct_params,
            )
            grads = jax.tree.map(jnp.add, grads, ct_params)

            fwd_next = jax.lax.ppermute(h_out, AXIS_PP, fwd_ring)
            bwd_next = jax.lax.ppermute(ct_h, AXIS_PP, bwd_ring)
            return (ring, fwd_next, bwd_next, grads, num, aux, dr, rt), None

        zs = jnp.zeros(())
        init = (
            jnp.zeros((V, RV, mb, s_len, cfg.d_model), dt),
            jnp.zeros((mb, s_len, cfg.d_model), dt),
            jnp.zeros((mb, s_len, cfg.d_model), dt),
            zero_grads,
            zs, zs, zs, zs,
        )
        (_, _, _, grads, num, aux, dr, rt), _ = jax.lax.scan(
            tick_masked if SP > 1 else tick, init, jnp.arange(T_ticks)
        )
        num_g = jax.lax.psum(num, (AXIS_PP, AXIS_DP))
        if has_moe:
            # den was computed BEFORE the scan (the aux seeds consume
            # it, which also serializes its psum against the scan).
            den_g, den_safe = den_pre, den_pre_safe
        else:
            # den is schedule-independent, but its dp psum must NOT
            # float freely against the scan's collectives: the CPU
            # backend's thunk executor runs independent collectives in
            # arbitrary per-device order, and a cross-device inversion
            # (one device parked in this all-reduce while its dp
            # partner waits inside a scan ppermute rendezvous)
            # deadlocks on a starved thread pool — observed on the
            # 8-virtual-device test rig, second step. Plain 1F1B is
            # naturally immune (its aux_seed makes the scan consume
            # den); here an optimization_barrier ties den's input to
            # num_g, pinning the psum strictly after the scan on every
            # device at zero math cost (a 0*num_g term could be
            # algebraically simplified away).
            w_dep = jax.lax.optimization_barrier((jnp.sum(w), num_g))[0]
            den_g = jax.lax.psum(w_dep, AXIS_DP)
            den_safe = jnp.maximum(den_g, 1.0)
        loss = num_g / den_safe
        if has_moe:
            # Same accounting as the other schedules: stages hold
            # disjoint MoE layers (psum over pp — each layer runs in
            # exactly one device's chunk), mean over microbatches and
            # dp shards; sp members hold disjoint sequence-shard
            # groups (sum over sp / SP).
            sp_axes = (AXIS_SP,) if SP > 1 else ()
            aux_g = jax.lax.psum(aux, (AXIS_PP, AXIS_DP) + sp_axes)
            loss = loss + aux_g / (n_micro * dp_n * SP)
            dr_g = jax.lax.psum(dr, (AXIS_PP, AXIS_DP) + sp_axes)
            rt_g = jax.lax.psum(rt, (AXIS_PP, AXIS_DP) + sp_axes)
            drop_fraction = dr_g / jnp.maximum(rt_g, 1.0)
        else:
            drop_fraction = jnp.zeros(())
        grads = jax.tree.map(lambda g: g / den_safe, grads)
        return loss, den_g, grads, drop_fraction

    def interleaved_eval_loss(params, x, y, w):
        """Forward-only interleaved schedule: the validation loss on
        the SAME (interleave-permuted) layer layout the train step
        runs — only the forward half of the schedule tables fires
        (the last forward entry lands at tick V*M + S - 2, so the
        scan runs V*M + S - 1 ticks). Same mask/cond discipline as
        ``interleaved_grads``."""
        stage = jax.lax.axis_index(AXIS_PP)
        b_local, s_len = x.shape
        if b_local % n_micro != 0:
            raise ValueError(
                f"local batch {b_local} not divisible by n_micro={n_micro}"
            )
        mb = b_local // n_micro
        micro_x = x.reshape(n_micro, mb, s_len)
        micro_y = y.reshape((n_micro, mb) + y.shape[1:])
        micro_w = w.reshape(n_micro, mb)
        M = n_micro
        fwd_ring = [(i, (i + 1) % S) for i in range(S)]

        def tw_of(mi):
            return (jnp.broadcast_to(micro_w[mi][:, None], (mb, s_len))
                    if has_moe else None)

        def tick(carry, t):
            fwd_ch, num, den = carry
            vf = fv_tab[t, stage]
            mf = fm_tab[t, stage]
            fwd_valid = vf >= 0
            vf_c = jnp.clip(vf, 0, V - 1)
            mf_c = jnp.clip(mf, 0, M - 1)

            def do_fwd():
                h_in = jax.lax.cond(
                    (vf_c == 0) & (stage == 0),
                    lambda: embed(params, micro_x[mf_c]),
                    lambda: fwd_ch,
                )
                h_out, _, _, _ = chunk_forward(params, vf_c, h_in,
                                               tw_of(mf_c))
                n_, d_ = jax.lax.cond(
                    (vf_c == V - 1) & (stage == S - 1),
                    lambda: head_loss(params, h_out, micro_y[mf_c],
                                      micro_w[mf_c]),
                    lambda: (jnp.zeros(()), jnp.zeros(())),
                )
                return h_out, n_, d_

            def skip_fwd():
                z = jnp.zeros((mb, s_len, cfg.d_model), dt)
                return z, jnp.zeros(()), jnp.zeros(())

            if SP > 1:
                # Masked-tick discipline (see tick_masked in
                # interleaved_grads): the chunk body's ring-attention
                # collectives must run every tick — do_fwd runs
                # UNCONDITIONALLY (its inner embed/head conds are
                # sp-uniform and never fire on clipped garbage) and
                # validity masks the accumulators instead.
                h_out, n_, d_ = do_fwd()
                fvv = fwd_valid.astype(jnp.float32)
                n_, d_ = fvv * n_, fvv * d_
            else:
                h_out, n_, d_ = jax.lax.cond(fwd_valid, do_fwd, skip_fwd)
            num = num + n_
            den = den + d_
            fwd_next = jax.lax.ppermute(h_out, AXIS_PP, fwd_ring)
            return (fwd_next, num, den), None

        init = (
            jnp.zeros((mb, s_len, cfg.d_model), dt),
            jnp.zeros(()), jnp.zeros(()),
        )
        # Every forward entry lands by tick V*M + S - 2 (the combined
        # schedule's later ticks are backward-only).
        (_, num, den), _ = jax.lax.scan(
            tick, init, jnp.arange(V * M + S - 1)
        )
        num_g = jax.lax.psum(num, (AXIS_PP, AXIS_DP))
        den_g = jax.lax.psum(den, (AXIS_PP, AXIS_DP))
        return num_g / jnp.maximum(den_g, 1.0)

    def local_step(params, opt_state, x, y, w, key):
        dp_idx = jax.lax.axis_index(AXIS_DP)

        def one(carry, sub):
            params, opt_state = carry
            if (mini_batch is not None and mini_batch > 0
                    and mini_batch > x.shape[0]):
                # Fail loudly (trace-time): silently training on the
                # full resident batch would be the quiet failure mode
                # the knob contract forbids. == resident size is the
                # documented identity case.
                raise ValueError(
                    f"mini_batch={mini_batch} exceeds the {x.shape[0]} "
                    "resident rows per dp shard"
                )
            if mini_batch is not None and 0 < mini_batch < x.shape[0]:
                from sparktorch_tpu.utils.data import sample_minibatch

                # Fold in the dp index: each dp shard samples its own
                # block, but pp/tp members of the same dp row MUST
                # sample the same rows (they cooperate on one batch).
                b = sample_minibatch(
                    DataBatch(x=x, y=y, w=w),
                    jax.random.fold_in(sub, dp_idx), mini_batch,
                )
            else:
                b = DataBatch(x=x, y=y, w=w)
            if schedule == "1f1b" and V > 1:
                loss, examples, grads, drop_fraction = interleaved_grads(
                    params, b.x, b.y, b.w
                )
            elif schedule == "1f1b":
                loss, examples, grads, drop_fraction = one_f_one_b_grads(
                    params, b.x, b.y, b.w
                )
            else:
                (loss, (drop_fraction, _, examples)), grads = (
                    jax.value_and_grad(
                        lambda p: schedule_loss(p, b.x, b.y, b.w),
                        has_aux=True,
                    )(params)
                )
                # psum under shard_map autodiff transposes to psum, so
                # the cotangent of the (pp, dp)-psummed loss arrives
                # SUMMED over those S*dp members: without this
                # normalization the effective gradient (and therefore
                # the SGD learning rate) grew linearly with mesh size.
                # Found by the 1f1b exactness test, whose manual
                # backward computes the honest mesh-size-invariant
                # gradient; dp=1/pp=1 agreement pins the right scale.
                grads = jax.tree.map(
                    lambda g: g / (S * mesh.shape[AXIS_DP]), grads
                )
            # Replicated-param grads must be summed over every axis
            # the param is replicated across: layer stacks live on one
            # pp shard each (sum over dp only); embed/head/norm are
            # used on all stages (masked elsewhere -> zero grads) and
            # replicated over both axes. No tp reductions anywhere:
            # the f/g pair in _layer_forward already makes every grad
            # complete and tp-identical. With ep>1, _ep_enter keeps
            # every grad ep-replicated EXCEPT the router's, whose
            # per-member share must additionally sum over ep (expert
            # leaves are ep-SHARDED and need no ep reduction).
            # With sp>1 each member trained on its SEQUENCE shard, so
            # every param grad is a per-shard share: sp joins dp in
            # the data axes every reduction sums over — MoE leaves
            # included (their routing groups partition over sp too).
            data_axes = (AXIS_DP,) + ((AXIS_SP,) if SP > 1 else ())

            def _reduce_moe(path, g):
                names = _path_names(path)
                if E > 1 and "router" in names:
                    return jax.lax.psum(g, data_axes + (AXIS_EP,))
                return jax.lax.psum(g, data_axes)

            from jax.tree_util import tree_map_with_path

            grads = {
                k: (
                    jax.tree.map(lambda g: jax.lax.psum(g, data_axes), v)
                    if k == "layers"
                    else tree_map_with_path(_reduce_moe, v)
                    if k == "layers_moe"
                    else jax.tree.map(
                        lambda g: jax.lax.psum(g, (AXIS_PP,) + data_axes), v
                    )
                )
                for k, v in grads.items()
            }
            updates, new_opt = tx.update(grads, opt_state, params)
            new_params = optax.apply_updates(params, updates)
            # Post-reduction grads are complete on every shard for the
            # params that shard owns: expert leaves are distinct per
            # (pp, ep) shard; other layer-stack squares distinct per
            # pp stage (dp/tp/ep-identical); embed/head/norm identical
            # everywhere. One FULL-mesh psum (the same collective
            # family the loss uses) with static 1/extent weights
            # counts each square exactly once in the global norm.
            S_pp = mesh.shape[AXIS_PP]
            S_dp = mesh.shape[AXIS_DP]
            E_ax = E if E > 1 else 1
            T_ax = T if T > 1 else 1
            SP_ax = SP if SP > 1 else 1
            norm_axes = (
                (AXIS_PP, AXIS_DP)
                + ((AXIS_EP,) if E > 1 else ())
                + ((AXIS_TP,) if T > 1 else ())
                + ((AXIS_SP,) if SP > 1 else ())
            )

            def _sq_moe(path, g):
                names = _path_names(path)
                # Expert leaves are distinct per (pp, ep) shard; the
                # rest of the MoE layer is ep-replicated; everything
                # is sp-replicated post-reduction. (tp is rejected
                # with MoE, so no tp term here.)
                w_ = (1.0 / (S_dp * SP_ax)
                      if names[-1] in _MOE_EXPERT_LEAVES
                      else 1.0 / (S_dp * E_ax * SP_ax))
                return jnp.sum(jnp.square(g)) * w_

            def _sq_layers(path, g):
                names = _path_names(path)
                # qkv/proj/mlp leaves are tp-SHARDED (distinct per
                # (pp, tp) shard); ln and output-side biases are
                # tp-replicated. Dense stacks are ep-replicated, and
                # every param is sp-replicated (post-reduction grads
                # identical across sp).
                is_tp_sharded = any(
                    names[-len(key):] == key for key in _TP_LAYER_DIMS
                )
                w_ = (1.0 / (S_dp * E_ax * SP_ax) if is_tp_sharded
                      else 1.0 / (S_dp * E_ax * T_ax * SP_ax))
                return jnp.sum(jnp.square(g)) * w_

            sq = {
                k: (
                    sum(jax.tree.leaves(tree_map_with_path(_sq_moe, v)))
                    if k == "layers_moe"
                    else sum(jax.tree.leaves(
                        tree_map_with_path(_sq_layers, v)))
                    if k == "layers"
                    else sum(jnp.sum(jnp.square(g))
                             for g in jax.tree.leaves(v))
                    * (1.0 / (S_dp * S_pp * E_ax * T_ax * SP_ax))
                )
                for k, v in grads.items()
            }
            grad_norm = jnp.sqrt(jax.lax.psum(sum(sq.values()), norm_axes))
            return (new_params, new_opt), (
                loss, drop_fraction, grad_norm, examples
            )

        (params, opt_state), outs = jax.lax.scan(
            one, (params, opt_state), jax.random.split(key, K)
        )
        loss, drop_fraction, grad_norm, examples = outs
        return params, opt_state, loss, drop_fraction, grad_norm, examples

    cache = {}
    # Data layout: rows over dp; with sp>1 the SEQUENCE dim of x (and
    # of token-level lm targets) shards over sp — classifier labels
    # are per-row and stay dp-only. Weights are per-row everywhere.
    x_spec = P(AXIS_DP, AXIS_SP) if SP > 1 else P(AXIS_DP)
    y_spec = x_spec if head == "lm" else P(AXIS_DP)

    def _build_eval(specs):
        """Forward-only schedule for validation: same pipeline, no
        grads, reporting the TASK loss (the [1][1] aux slot — sown MoE
        aux objectives are excluded from the validation signal, like
        the DP eval)."""
        if V > 1:
            # The GPipe eval walks each device's local stack in stage
            # order, which would be SCRAMBLED under the interleaved
            # layout — eval with the forward half of the interleaved
            # schedule instead (same chunk walk as training).
            eval_mapped = shard_map_compat(
                interleaved_eval_loss,
                mesh,
                in_specs=(specs, x_spec, y_spec, P(AXIS_DP)),
                out_specs=P(),
            )
            return jax.jit(eval_mapped)
        eval_mapped = shard_map_compat(
            lambda p, x, y, w: schedule_loss(p, x, y, w)[1][1],
            mesh,
            in_specs=(specs, x_spec, y_spec, P(AXIS_DP)),
            out_specs=P(),
        )
        return jax.jit(eval_mapped)

    def _ensure_built(state: PipelineState):
        if "jitted" not in cache:
            specs = _param_specs(state.params)
            opt_specs = _opt_specs(tx, state.opt_state, specs)
            mapped = shard_map_compat(
                local_step,
                mesh,
                in_specs=(specs, opt_specs,
                          x_spec, y_spec, P(AXIS_DP), P()),
                out_specs=(specs, opt_specs, P(), P(), P(), P()),
            )
            cache["jitted"] = jax.jit(mapped, donate_argnums=(0, 1))
            cache["eval"] = _build_eval(specs)

    def memory_analysis(state: PipelineState, batch: DataBatch, key=None):
        """XLA's memory analysis of the compiled train step (temp
        allocation bytes etc.) — how the 1f1b-vs-gpipe activation-
        memory claim is MEASURED rather than asserted. Call before
        stepping (lowering uses the live buffers; no donation)."""
        _ensure_built(state)
        k = key if key is not None else jax.random.key(0)
        return cache["jitted"].lower(
            state.params, state.opt_state, batch.x, batch.y, batch.w, k
        ).compile().memory_analysis()

    def step(state: PipelineState, batch: DataBatch, key=None):
        _ensure_built(state)
        if key is None:
            if mini_batch is None and K == 1:
                # The key is never consumed on this configuration —
                # any constant avoids the device sync a
                # device_get(state.step) fold would cost per call.
                key = cache.setdefault("zero_key", jax.random.key(0))
            else:
                # Deterministic per-call key for minibatch sampling:
                # a host-side step counter seeded from the device step,
                # so fresh blocks are drawn each call without a
                # per-call device sync. The counter is resynced (one
                # device_get) whenever the caller passes a state this
                # step fn did NOT produce — a restored checkpoint or a
                # fresh PipelineState — detected by identity on the
                # step array, so resumed runs key off the true
                # state.step instead of a stale cache (ADVICE r04).
                if ("host_step" not in cache
                        or state.step is not cache.get("last_step_arr")):
                    # One scalar, only on resume/cache invalidation —
                    # steady state uses the host mirror.
                    # lint-obs: ok (resume-only scalar)
                    cache["host_step"] = int(jax.device_get(state.step))
                key = jax.random.fold_in(
                    jax.random.key(0), cache["host_step"]
                )
                cache["host_step"] += K
        new_params, new_opt, loss, drop, grad_norm, examples = cache[
            "jitted"
        ](state.params, state.opt_state, batch.x, batch.y, batch.w, key)
        if jax.default_backend() == "cpu":
            # The in-process CPU collectives runtime keys its
            # rendezvous on a run id that COLLIDES across overlapping
            # launches of the same executable; donation orders buffer
            # reuse but not execution tails, so back-to-back steps can
            # overlap and flakily mix rendezvous (observed as a 9th
            # participant at an 8-thread collective permute, or a
            # cross-collective deadlock). The virtual-device test rig
            # serializes executions instead; real TPU stays async.
            # lint-obs: ok (deliberate CPU-only rendezvous serialization)
            jax.block_until_ready((new_params, new_opt, loss))
        new_state = PipelineState(step=state.step + K, params=new_params,
                                  opt_state=new_opt)
        cache["last_step_arr"] = new_state.step
        if K == 1:
            # Introspection hooks (concrete post-jit values), same
            # single-step contract as before for existing callers.
            step.last_drop_fraction = float(drop[0]) if has_moe else None
            step.last_grad_norm = float(grad_norm[0])
            step.last_examples = float(examples[0])
            return new_state, loss[0]
        return new_state, PpStepOut(
            loss=loss, drop_fraction=drop if has_moe else None,
            grad_norm=grad_norm, examples=examples,
        )

    def eval_loss(state: PipelineState, batch: DataBatch):
        if "eval" not in cache:
            cache["eval"] = _build_eval(_param_specs(state.params))
        return cache["eval"](state.params, batch.x, batch.y, batch.w)

    step.eval_loss = eval_loss
    step.memory_analysis = memory_analysis
    # Goodput compile detection: the trainer probes the lazily-built
    # jitted's dispatch-cache size around each call (None until the
    # first _ensure_built, which reads as "no signal").
    step.jit_cache_size = (
        lambda: _goodput.jit_cache_size(cache.get("jitted")))
    return step


def _opt_specs(tx, opt_state, param_specs):
    """Optimizer leaves that mirror the param TREE (Adam moments etc.)
    inherit the matching param's spec exactly — structural matching
    via ``optax.tree_map_params``, not shape heuristics (two params
    can share a shape); every non-param leaf replicates."""
    return optax.tree_map_params(
        tx,
        lambda _, spec: spec,
        opt_state,
        param_specs,
        transform_non_params=lambda _: P(),
        is_leaf=lambda x: isinstance(x, P),
    )


# ---------------------------------------------------------------------------
# ModelSpec / estimator integration: pp as a mesh-config choice
# ---------------------------------------------------------------------------


def pipeline_params_from_flax(params, cfg: TransformerConfig):
    """Convert a ``CausalLM`` (untied) or ``SequenceClassifier`` flax
    param tree into the pipeline's stacked layout (dense and MoE
    layers into their separate stacks). Inverse of
    :func:`flax_params_from_pipeline`."""
    bb = params["backbone"]
    pattern = _moe_pattern(cfg)
    out = {
        "tok_embed": bb["tok_embed"]["embedding"],
        "pos_embed": bb["pos_embed"],
        "ln_scale": bb["ln_final"]["scale"],
        "ln_bias": bb["ln_final"]["bias"],
    }
    dense = [bb[f"layer_{i}"] for i in range(cfg.n_layers) if not pattern[i]]
    moe = [bb[f"layer_{i}"] for i in range(cfg.n_layers) if pattern[i]]
    if dense:
        out["layers"] = jax.tree.map(lambda *xs: jnp.stack(xs), *dense)
    if moe:
        out["layers_moe"] = jax.tree.map(lambda *xs: jnp.stack(xs), *moe)
    if "lm_head" in params:
        out["head_w"] = params["lm_head"]["kernel"]
        out["head_b"] = params["lm_head"]["bias"]
    else:
        out["pool_w"] = params["pooler"]["kernel"]
        out["pool_b"] = params["pooler"]["bias"]
        out["cls_w"] = params["classifier"]["kernel"]
        out["cls_b"] = params["classifier"]["bias"]
    return out


def flax_params_from_pipeline(pparams, cfg: TransformerConfig):
    """Back to the ``CausalLM`` / ``SequenceClassifier`` flax tree (so
    the fitted bundle transforms through the ordinary module apply)."""
    pattern = _moe_pattern(cfg)
    bb = {}
    jd = jm = 0
    for i in range(cfg.n_layers):
        if pattern[i]:
            k = jm
            bb[f"layer_{i}"] = jax.tree.map(
                lambda a, k=k: a[k], pparams["layers_moe"]
            )
            jm += 1
        else:
            k = jd
            bb[f"layer_{i}"] = jax.tree.map(
                lambda a, k=k: a[k], pparams["layers"]
            )
            jd += 1
    bb["tok_embed"] = {"embedding": pparams["tok_embed"]}
    bb["pos_embed"] = pparams["pos_embed"]
    bb["ln_final"] = {"scale": pparams["ln_scale"],
                      "bias": pparams["ln_bias"]}
    if "head_w" in pparams:
        return {
            "backbone": bb,
            "lm_head": {"kernel": pparams["head_w"],
                        "bias": pparams["head_b"]},
        }
    return {
        "backbone": bb,
        "pooler": {"kernel": pparams["pool_w"], "bias": pparams["pool_b"]},
        "classifier": {"kernel": pparams["cls_w"], "bias": pparams["cls_b"]},
    }


def train_distributed_pipeline(
    spec,
    data,
    labels=None,
    mesh: Optional[Mesh] = None,
    iters: int = 10,
    n_micro: int = 4,
    verbose: int = 0,
    seed: int = 0,
    metrics_hook=None,
    checkpoint_dir: Optional[str] = None,
    checkpoint_every: int = 0,
    resume: bool = False,
    partition_shuffles: int = 1,
    early_stop_patience: int = -1,
    validation_pct: float = 0.0,
    mini_batch: Optional[int] = None,
    steps_per_call: Optional[int] = None,
    profile_dir: Optional[str] = None,
    schedule: str = "gpipe",
    virtual_stages: int = 1,
    pre_sharded: bool = False,
    telemetry=None,
):
    """Pipelined training entry for a ``ModelSpec`` holding a
    ``CausalLM`` — the dispatch target ``train_distributed`` uses when
    the mesh has pp > 1, so pp is a MESH choice on the ordinary
    Estimator/ModelSpec surface, not a separate API.

    The spec's flax params are initialized normally, restacked into
    the pipeline layout, trained under the GPipe schedule, and
    unstacked back — the returned ``TrainResult`` bundles ordinary
    ``CausalLM`` params that transform through the module apply.
    """
    from sparktorch_tpu.models.transformer import CausalLM, SequenceClassifier
    from sparktorch_tpu.obs import get_logger, get_telemetry
    from sparktorch_tpu.parallel.launch import check_gang, notify_gang_step
    from sparktorch_tpu.train.sync import TrainResult
    from sparktorch_tpu.utils.metrics import MetricsRecorder

    tele = telemetry or get_telemetry()
    log = get_logger("sparktorch_tpu.train")
    # Stack sampler beside the ambient ledger (see train/sync.py).
    from sparktorch_tpu.ft import chaos as _chaos
    from sparktorch_tpu.obs import health as _health
    from sparktorch_tpu.obs import profile as _profile

    _profile.ensure(tele)
    _hl = _health.ensure(tele, rank=jax.process_index())
    if _hl is not None:
        _hl.reset()

    module = spec.make_module()
    if isinstance(module, CausalLM):
        head = "lm"
    elif isinstance(module, SequenceClassifier):
        head = "classifier"
    else:
        raise ValueError(
            "pipeline-parallel training (mesh pp>1) supports CausalLM "
            f"and SequenceClassifier specs; got {type(module).__name__}. "
            "Use a mesh with pp=1 for other model families."
        )
    cfg = module.config
    if cfg.tie_embeddings:
        raise ValueError("pp training does not support tie_embeddings yet")
    if spec.loss not in ("cross_entropy", "cross_entropy_fused", "nll"):
        raise ValueError(
            f"pp training uses cross entropy; got {spec.loss!r}"
        )

    if pre_sharded:
        # ``data`` is a globally-sharded DataBatch (multi-host path:
        # per-process shards assembled by train_distributed_multihost
        # via make_array_from_process_local_data). No host-side
        # conversion is possible — or needed: validate shapes, cast on
        # device (sharding-preserving), and train on it directly.
        if not isinstance(data, DataBatch):
            raise ValueError(
                "pre_sharded pp training expects a DataBatch of global "
                f"arrays; got {type(data).__name__}"
            )
        if validation_pct and validation_pct > 0:
            raise ValueError(
                "validation_pct is not supported with pre_sharded pp "
                "data — split before assembling the global batch"
            )
        dp = mesh.shape[AXIS_DP]
        rows = int(data.x.shape[0])
        if rows % dp != 0 or (rows // dp) % n_micro != 0:
            raise ValueError(
                f"pre_sharded rows ({rows}) must divide dp ({dp}) x "
                f"n_micro ({n_micro}); pad with weight-0 rows before "
                "sharding (train_distributed_multihost does this)"
            )
        sp_ = dict(mesh.shape).get(AXIS_SP, 1)
        if sp_ > 1 and int(data.x.shape[1]) % sp_ != 0:
            raise ValueError(
                f"sequence length {data.x.shape[1]} not divisible by "
                f"sp={sp_}"
            )
        cast = jax.jit(lambda a: a.astype(jnp.int32))
        batch = DataBatch(x=cast(data.x), y=cast(data.y), w=data.w)
        val_batch = None
        n_rows_padded = rows
        sample_x = np.zeros((1, int(batch.x.shape[1])), np.int32)
    elif isinstance(data, DataBatch):
        x = np.asarray(data.x)
        y = np.asarray(data.y)
        w = np.asarray(data.w, dtype=np.float32)
    elif (isinstance(data, tuple) and len(data) == 2 and labels is None):
        # The (x, y) tuple form _as_batch accepts on the pp=1 path.
        x = np.asarray(data[0])
        y = np.asarray(data[1])
        w = np.ones((x.shape[0],), np.float32)
    else:
        x = np.asarray(data)
        y = np.asarray(labels) if labels is not None else None
        if y is None:
            if head == "classifier":
                raise ValueError("classifier pp training requires labels")
            x, y = x[:, :-1], x[:, 1:]  # next-token LM on one id matrix
        w = np.ones((x.shape[0],), np.float32)
    if not pre_sharded:
        x = x.astype(np.int32)
        y = y.astype(np.int32)

        sp = dict(mesh.shape).get(AXIS_SP, 1)
        if sp > 1 and x.shape[1] % sp != 0:
            raise ValueError(
                f"sequence length {x.shape[1]} not divisible by sp={sp}"
            )

        from sparktorch_tpu.utils.data import pad_to_multiple

        dp = mesh.shape[AXIS_DP]
        need = dp * n_micro

        def _pad_batch(bx, by, bw):
            return pad_to_multiple(
                DataBatch(x=jnp.asarray(bx), y=jnp.asarray(by),
                          w=jnp.asarray(bw)),
                need,
            )

        val_batch = None
        if validation_pct and validation_pct > 0:
            # Split BEFORE padding (the reference's per-worker holdout,
            # util.py:81-95): a shuffled cut of real rows, keeping any
            # caller-supplied sample weights.
            perm0 = np.random.default_rng(seed).permutation(x.shape[0])
            n_val = max(1, int(x.shape[0] * validation_pct))
            val_idx, train_idx = perm0[:n_val], perm0[n_val:]
            if train_idx.size == 0:
                raise ValueError("validation_pct leaves no training rows")
            val_batch = _pad_batch(x[val_idx], y[val_idx], w[val_idx])
            x, y, w = x[train_idx], y[train_idx], w[train_idx]
        batch = _pad_batch(x, y, w)
        n_rows_padded = int(batch.x.shape[0])
        sample_x = x[:1]

    if mini_batch is not None and mini_batch > 0:
        per_shard = n_rows_padded // dp
        if mini_batch > per_shard:
            raise ValueError(
                f"mini_batch={mini_batch} exceeds the {per_shard} "
                f"resident rows per dp shard"
            )
    else:
        mini_batch = None

    # Chunking mirrors the DP trainer (the shared contract lives in
    # sync._resolve_steps_per_call): fuse many schedules per compiled
    # call unless early stopping / validation need a signal at every
    # step (the pp path checks those at call boundaries, so their
    # cadence IS the chunk size).
    from sparktorch_tpu.train.sync import _resolve_steps_per_call

    steps_per_call = _resolve_steps_per_call(
        steps_per_call,
        default=(
            1
            if (early_stop_patience and early_stop_patience > 0)
            or validation_pct > 0
            else min(iters, 16)
        ),
        iters=iters,
        checkpoint_every=checkpoint_every,
        ckpt_active=bool(checkpoint_dir),
    )
    if (steps_per_call > 1
            and ((early_stop_patience and early_stop_patience > 0)
                 or validation_pct > 0)):
        # The default resolution already picks 1 when these signals
        # are active, so reaching here means an EXPLICIT override:
        # make the cadence change loud rather than silent (ADVICE
        # r04 — patience would otherwise silently multiply by the
        # chunk size).
        import warnings

        warnings.warn(
            f"steps_per_call={steps_per_call} with early stopping / "
            "validation on the pp path: the stop signal and val loss "
            "are evaluated at COMPILED-CALL boundaries, so "
            "early_stop_patience counts calls (each "
            f"{steps_per_call} steps), not steps",
            stacklevel=2,
        )

    tx = spec.make_optimizer()
    # Build the step FIRST: its config validation (stage divisibility,
    # MoE pattern uniformity, tp x MoE) produces actionable errors;
    # placement would otherwise fail earlier with a raw sharding error.
    step = make_pp_train_step(cfg, tx, mesh, n_micro=n_micro, head=head,
                              mini_batch=mini_batch,
                              steps_per_call=steps_per_call,
                              schedule=schedule,
                              virtual_stages=virtual_stages)
    rng = jax.random.key(seed)
    flax_params = dict(spec.init_params(rng, sample_x=sample_x))["params"]
    pparams = pipeline_params_from_flax(flax_params, cfg)
    interleaved = bool(virtual_stages and virtual_stages > 1)
    if interleaved:
        # Interleaved layout: re-order the stacked layers (each kind's
        # stack with its own permutation) so device d's contiguous pp
        # shard holds its V chunks (undone below so the returned
        # params are in ordinary flax order).
        pparams = apply_interleave_permutation(
            pparams, cfg, mesh.shape[AXIS_PP], virtual_stages
        )
    state = place_pipeline_state(pparams, tx, mesh)

    from sparktorch_tpu.train.sync import (
        _finalize_checkpoint,
        _open_checkpoint,
        _save_if_due,
    )

    # Checkpointed stacks are stored in the SCHEDULE'S layer order
    # (interleave-permuted under virtual_stages>1) — a layout marker
    # makes a mismatched resume fail loudly instead of silently
    # training a scrambled model.
    if checkpoint_dir:
        import json
        import os

        layout = {
            "pp": int(mesh.shape[AXIS_PP]),
            "virtual_stages": int(virtual_stages or 1),
        }
        layout_path = os.path.join(checkpoint_dir, "pipeline_layout.json")
        if resume and os.path.exists(layout_path):
            with open(layout_path) as f:
                saved = json.load(f)
            if saved != layout:
                raise ValueError(
                    f"checkpoint layer layout {saved} does not match the "
                    f"requested {layout}: the stacked layers are stored "
                    "in the schedule's permuted order — resume with the "
                    "same pp and virtual_stages"
                )
        elif jax.process_index() == 0:
            # One writer, atomic rename: concurrent gang processes
            # sharing a checkpoint dir must never see a torn marker.
            os.makedirs(checkpoint_dir, exist_ok=True)
            tmp = layout_path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(layout, f)  # lint-obs: ok (checkpoint layout)
            os.replace(tmp, layout_path)

    # PipelineState checkpoints like TrainState (step-indexed orbax
    # snapshots restored INTO the pp/tp-sharded layout).
    ckpt, state = _open_checkpoint(checkpoint_dir, resume, state)

    from sparktorch_tpu.utils.early_stopper import EarlyStopping

    stopper = (
        EarlyStopping(patience=early_stop_patience)
        if early_stop_patience is not None and early_stop_patience > 0
        else None
    )
    recorder = MetricsRecorder(n_chips=mesh.size, telemetry=tele,
                               prefix="train_pp")
    # lint-obs: ok (two scalars before the loop starts — nothing queued)
    last_ckpt = int(jax.device_get(state.step)) if ckpt is not None else 0
    start = int(jax.device_get(state.step))  # lint-obs: ok (pre-loop scalar)
    # Seed folded with the restored step: a resumed run must draw
    # FRESH permutations, not replay the interrupted run's (same
    # invariant as the streaming trainer's resume seeding).
    shuffle_rng = np.random.default_rng(seed + 1 + start)
    # On-device permutation: one small index upload per round instead
    # of re-uploading the full x/y/w arrays from the host.
    permute = jax.jit(
        lambda b, p: DataBatch(x=b.x[p], y=b.y[p], w=b.w[p])
    )
    from sparktorch_tpu.utils.tracing import profile_run, step_annotation

    sample_key = jax.random.key(seed + 2 + start)
    completed = False
    stop = False
    profiler = profile_run(profile_dir, telemetry=tele)
    profiler.__enter__()
    try:
        for shuffle_round in range(max(1, partition_shuffles)):
            # Round 0 must ALSO shuffle when minibatch sampling is on:
            # sample_minibatch takes contiguous blocks, whose
            # uniformity argument requires random resident order (the
            # same invariant as the DP trainer).
            if shuffle_round > 0 or mini_batch is not None:
                # The reference's partition reshuffle between rounds
                # (distributed.py:267-273): microbatch membership
                # changes; weight-0 padding rows stay masked wherever
                # they land.
                batch = permute(
                    batch,
                    jnp.asarray(shuffle_rng.permutation(n_rows_padded)),
                )
            i = 0
            while i < iters:
                # Same pre-dispatch liveness check + progress publish
                # as the DP trainer: a dead peer aborts before the next
                # compiled schedule (instead of wedging in its
                # collectives), and this rank's step lands on its gang
                # heartbeat so the driver can read cross-rank skew.
                check_gang()
                notify_gang_step(i)
                _act = _chaos.fire("data.batch",
                                   worker=jax.process_index(), step=i)
                if _act and _act.get("poison"):
                    batch = _chaos.poison_batch(batch)
                # Straggler injection before the step span: a late
                # fence arrival the skew referee can attribute.
                _chaos.straggle(jax.process_index(), i)
                sample_key, sub = jax.random.split(sample_key)
                # Goodput step clock: dispatch + loss materialization
                # timed by a LedgerSpan (step_time_s comes off its
                # duration; the seconds land in the ledger's step
                # bucket when one is armed, re-aimed at ``compile``
                # when the jitted's dispatch cache grew under it).
                cache0 = (step.jit_cache_size()
                          if _goodput.active() is not None else None)
                with _goodput.step_span(step=i) as _led:
                    with tele.span("train_pp/step_call"), \
                            step_annotation(i, telemetry=tele):
                        state, out = step(state, batch, key=sub)
                    if steps_per_call == 1:
                        losses = [float(out)]
                        gnorms = [step.last_grad_norm]
                        exs = [step.last_examples]
                        drops = [step.last_drop_fraction]
                    else:
                        losses = [float(v) for v in np.asarray(out.loss)]
                        gnorms = [float(v) for v in np.asarray(out.grad_norm)]
                        exs = [float(v) for v in np.asarray(out.examples)]
                        drops = (
                            [float(v) for v in np.asarray(out.drop_fraction)]
                            if out.drop_fraction is not None
                            else [None] * steps_per_call
                        )
                    _led.count = len(losses)
                    if cache0 is not None and (
                            step.jit_cache_size() or cache0) > cache0:
                        _led.rebucket("compile")
                # Time the once-per-call eval separately: smearing it
                # into the per-step dt would inflate step_time_s by
                # eval_wall/steps_per_call (ADVICE r04). Productive
                # device work, so the ledger files it under compute.
                with _goodput.span("compute", {"site": "pp_eval"}) \
                        as _eval_led:
                    val_loss = (
                        float(step.eval_loss(state, val_batch))
                        if val_batch is not None else None
                    )
                eval_s = _eval_led.duration_s
                dt = _led.duration_s / len(losses)
                if _hl is not None:
                    # Loss/grad-norm are already host floats here (the
                    # step call materializes them); the ledger still
                    # applies its detectors on the K-late cadence.
                    _hl.note_step(count=len(losses),
                                  host={"loss": np.asarray(losses),
                                        "grad_norm": np.asarray(
                                            [g if g is not None else np.nan
                                             for g in gnorms])})
                for j, (l, g, e, dr) in enumerate(
                    zip(losses, gnorms, exs, drops)
                ):
                    record = {
                        "round": shuffle_round, "iter": i + j,
                        "loss": l,
                        # val runs once per call, on the post-call
                        # params: attach it to the chunk's last step.
                        "val_loss": (val_loss if j == len(losses) - 1
                                     else None),
                        "examples": e,
                        "grad_norm": g,
                        "step_time_s": dt,
                    }
                    if val_loss is not None and j == len(losses) - 1:
                        record["eval_time_s"] = eval_s
                    if dr is not None:
                        record["moe_drop_fraction"] = dr
                    recorder.record(record)
                    if metrics_hook:
                        metrics_hook(record)
                    if verbose:
                        msg = (f"[sparktorch_tpu:pp] round {shuffle_round} "
                               f"iter {i + j} loss {l:.6f}")
                        if record["val_loss"] is not None:
                            msg += f" val_loss {record['val_loss']:.6f}"
                        log.info(msg)
                i += len(losses)
                if ckpt is not None:
                    with tele.span("train_pp/checkpoint"):
                        last_ckpt = _save_if_due(ckpt, state, last_ckpt,
                                                 checkpoint_every)
                # The global loss is replicated on every host, so the
                # per-host stopper reaches the identical decision (no
                # extra collective — same argument as the DP trainer).
                # With steps_per_call > 1 the signal cadence is the
                # call boundary (patience counts calls, not steps).
                if stopper is not None and stopper.step(
                    val_loss if val_loss is not None else losses[-1]
                ):
                    stop = True
                    break
            if stop:
                break
        completed = True
    finally:
        if _hl is not None:
            _hl.flush()
        profiler.__exit__(None, None, None)
        _finalize_checkpoint(ckpt, state, completed)

    if jax.process_count() > 1:
        # The pp/tp-sharded stacks span non-addressable devices in a
        # multi-process world: gather to replicated (one all-gather)
        # so every host returns the full params — the DP multihost
        # path's contract.
        from sparktorch_tpu.parallel.mesh import replicated as _replicated

        gather = jax.jit(
            lambda p: p,
            out_shardings=jax.tree.map(lambda _: _replicated(mesh),
                                       state.params),
        )
        # lint-obs: ok (end-of-run gather after the loop drained)
        trained = jax.device_get(gather(state.params))
    else:
        trained = jax.device_get(state.params)  # lint-obs: ok (end-of-run)
    if interleaved:
        trained = apply_interleave_permutation(
            trained, cfg, mesh.shape[AXIS_PP], virtual_stages,
            inverse=True,
        )
    out_params = flax_params_from_pipeline(trained, cfg)
    return TrainResult(params=out_params, model_state={},
                       metrics=recorder.records, spec=spec,
                       summary=recorder.summary())
