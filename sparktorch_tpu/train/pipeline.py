"""GPipe pipeline parallelism over the ``pp`` mesh axis.

No reference counterpart (SURVEY §2.4: PP "absent"). TPU-first
design: the transformer stack is split into ``pp`` stages — the
stacked per-layer params are sharded over ``pp`` on their leading
(layer) dim — and a ``shard_map`` step runs the classic GPipe
schedule: microbatches enter at stage 0, activations hop stage→stage
on an ICI ring via ``lax.ppermute``, the last stage accumulates the
weighted loss, and autodiff THROUGH the schedule (ppermute transposes
to the reverse permute) yields exact gradients — mathematically
identical to gradient accumulation over the microbatches on one
device, which is what the parity test asserts.

The whole schedule (M + S - 1 ticks) is one ``lax.scan`` inside one
jitted ``shard_map``: zero per-tick Python, static shapes, and the
bubble is the textbook (S-1)/(M+S-1) fraction — raise ``n_micro`` to
shrink it.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sparktorch_tpu.models.transformer import EncoderLayer, TransformerConfig
from sparktorch_tpu.parallel.mesh import AXIS_DP, AXIS_PP
from sparktorch_tpu.train.step import shard_map_compat
from sparktorch_tpu.utils.data import DataBatch


class PipelineState(NamedTuple):
    step: jax.Array
    params: Any
    opt_state: Any


def init_pipeline_lm(cfg: TransformerConfig, key: jax.Array):
    """Host-side init of a causal LM laid out for pipelining: the
    encoder layers' params are STACKED on a leading (n_layers) dim —
    the dim the pp sharding splits — plus replicated embedding / final
    norm / LM head tensors."""
    cfg = dataclasses.replace(cfg, causal=True)
    layer = EncoderLayer(cfg)
    k_embed, k_pos, k_head, k_layers = jax.random.split(key, 4)
    sample_h = jnp.zeros((1, cfg.max_len, cfg.d_model), cfg.compute_dtype)
    layer_keys = jax.random.split(k_layers, cfg.n_layers)
    stacked = jax.vmap(lambda k: layer.init(k, sample_h)["params"])(layer_keys)
    d = cfg.d_model
    params = {
        "layers": stacked,  # every leaf: (n_layers, ...)
        "tok_embed": jax.random.normal(k_embed, (cfg.vocab_size, d)) * 0.02,
        "pos_embed": jax.random.normal(k_pos, (cfg.max_len, d)) * 0.02,
        "ln_scale": jnp.ones((d,)),
        "ln_bias": jnp.zeros((d,)),
        "head_w": jax.random.normal(k_head, (d, cfg.vocab_size))
        * (1.0 / np.sqrt(d)),
        "head_b": jnp.zeros((cfg.vocab_size,)),
    }
    return params


def _param_specs(params) -> Any:
    """Per-leaf PartitionSpecs: layer stacks split over pp on their
    leading (layer) dim; everything else replicated."""
    return {
        k: (
            jax.tree.map(lambda _: P(AXIS_PP), v)
            if k == "layers"
            else jax.tree.map(lambda _: P(), v)
        )
        for k, v in params.items()
    }


def place_pipeline_state(params, tx, mesh: Mesh) -> PipelineState:
    """device_put params into their pipeline layout and init the
    optimizer on the placed arrays (eager optax init preserves input
    shardings leaf-wise)."""
    sh = jax.tree.map(
        lambda s: NamedSharding(mesh, s), _param_specs(params),
        is_leaf=lambda x: isinstance(x, P),
    )
    params = jax.tree.map(jax.device_put, params, sh)
    opt_state = tx.init(params)
    return PipelineState(step=jnp.zeros((), jnp.int32), params=params,
                         opt_state=opt_state)


def make_pp_train_step(
    cfg: TransformerConfig,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    n_micro: int,
) -> Callable[[PipelineState, DataBatch], Tuple[PipelineState, jax.Array]]:
    """Build the jitted pipelined train step over ``mesh`` (dp x pp;
    other axes must be 1 for this trainer)."""
    for ax in mesh.shape:
        if ax not in (AXIS_DP, AXIS_PP) and mesh.shape[ax] != 1:
            raise ValueError(f"pipeline trainer supports dp x pp only; {ax}>1")
    S = mesh.shape[AXIS_PP]
    if cfg.n_layers % max(1, S) != 0:
        raise ValueError(f"n_layers={cfg.n_layers} not divisible by pp={S}")
    # The pipelined stack is the homogeneous dense EncoderLayer; fail
    # loudly rather than silently training a different model.
    if cfg.n_experts > 0:
        raise ValueError("pipeline trainer does not support MoE layers yet")
    if cfg.remat:
        raise ValueError("pipeline trainer does not support remat yet")
    if cfg.attn_impl != "dense":
        # ring/flash open their own shard_map / Pallas islands, which
        # do not compose with the pp shard_map schedule yet.
        raise ValueError(
            f"pipeline trainer supports attn_impl='dense' only "
            f"(got {cfg.attn_impl!r})"
        )
    cfg = dataclasses.replace(cfg, causal=True)
    layer = EncoderLayer(cfg)
    dt = cfg.compute_dtype

    def stage_fn(local_layers, h):
        def body(h, lp):
            return layer.apply({"params": lp}, h), None

        h, _ = jax.lax.scan(body, h, local_layers)
        return h

    def embed(params, ids):
        s = ids.shape[1]
        h = params["tok_embed"][ids] + params["pos_embed"][None, :s]
        return h.astype(dt)

    def head_loss(params, h, y, w):
        hf = h.astype(jnp.float32)
        mean = hf.mean(-1, keepdims=True)
        var = ((hf - mean) ** 2).mean(-1, keepdims=True)
        hf = (hf - mean) / jnp.sqrt(var + 1e-6)
        hf = hf * params["ln_scale"] + params["ln_bias"]
        logits = hf @ params["head_w"] + params["head_b"]
        per_tok = optax.softmax_cross_entropy_with_integer_labels(logits, y)
        per_ex = per_tok.mean(-1)
        return jnp.sum(per_ex * w), jnp.sum(w)

    ring = [(i, (i + 1) % S) for i in range(S)]

    def local_step(params, opt_state, x, y, w):
        stage = jax.lax.axis_index(AXIS_PP)
        b_local, s = x.shape
        if b_local % n_micro != 0:
            raise ValueError(
                f"local batch {b_local} not divisible by n_micro={n_micro}"
            )
        mb = b_local // n_micro
        micro_x = x.reshape(n_micro, mb, s)
        micro_y = y.reshape(n_micro, mb, s)
        micro_w = w.reshape(n_micro, mb)

        def pipeline_loss(params):
            def tick(carry, t):
                h_prev, num, den = carry
                inj = jnp.clip(t, 0, n_micro - 1)
                # Only stage 0 embeds and only the last stage (inside
                # its valid drain window) runs the vocab-sized head —
                # lax.cond skips the dead branch at runtime instead of
                # computing it everywhere and masking to zero (the
                # head matmul + its backward dominate for real vocabs).
                h_in = jax.lax.cond(
                    stage == 0,
                    lambda: embed(params, micro_x[inj]),
                    lambda: h_prev,
                )
                h_out = stage_fn(params["layers"], h_in)
                m = t - (S - 1)
                mi = jnp.clip(m, 0, n_micro - 1)
                use = (m >= 0) & (m < n_micro) & (stage == S - 1)
                n_, d_ = jax.lax.cond(
                    use,
                    lambda: head_loss(params, h_out, micro_y[mi], micro_w[mi]),
                    lambda: (jnp.zeros(()), jnp.zeros(())),
                )
                num = num + n_
                den = den + d_
                h_next = jax.lax.ppermute(h_out, AXIS_PP, ring)
                return (h_next, num, den), None

            init_h = jnp.zeros((mb, s, cfg.d_model), dt)
            (_, num, den), _ = jax.lax.scan(
                tick, (init_h, jnp.zeros(()), jnp.zeros(())),
                jnp.arange(n_micro + S - 1),
            )
            num_g = jax.lax.psum(num, (AXIS_PP, AXIS_DP))
            den_g = jax.lax.psum(den, (AXIS_PP, AXIS_DP))
            return num_g / jnp.maximum(den_g, 1.0)

        loss, grads = jax.value_and_grad(pipeline_loss)(params)
        # Replicated-param grads must be summed over every axis the
        # param is replicated across: layer stacks live on one pp
        # shard each (sum over dp only); embed/head/norm are used on
        # all stages (masked elsewhere -> zero grads) and replicated
        # over both axes.
        grads = {
            k: (
                jax.tree.map(lambda g: jax.lax.psum(g, AXIS_DP), v)
                if k == "layers"
                else jax.tree.map(
                    lambda g: jax.lax.psum(g, (AXIS_PP, AXIS_DP)), v
                )
            )
            for k, v in grads.items()
        }
        updates, new_opt = tx.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        return new_params, new_opt, loss

    cache = {}

    def step(state: PipelineState, batch: DataBatch):
        if "jitted" not in cache:
            specs = _param_specs(state.params)
            opt_specs = _opt_specs(tx, state.opt_state, specs)
            mapped = shard_map_compat(
                local_step,
                mesh,
                in_specs=(specs, opt_specs,
                          P(AXIS_DP), P(AXIS_DP), P(AXIS_DP)),
                out_specs=(specs, opt_specs, P()),
            )
            cache["jitted"] = jax.jit(mapped, donate_argnums=(0, 1))
        new_params, new_opt, loss = cache["jitted"](
            state.params, state.opt_state, batch.x, batch.y, batch.w
        )
        return (
            PipelineState(step=state.step + 1, params=new_params,
                          opt_state=new_opt),
            loss,
        )

    return step


def _opt_specs(tx, opt_state, param_specs):
    """Optimizer leaves that mirror the param TREE (Adam moments etc.)
    inherit the matching param's spec exactly — structural matching
    via ``optax.tree_map_params``, not shape heuristics (two params
    can share a shape); every non-param leaf replicates."""
    return optax.tree_map_params(
        tx,
        lambda _, spec: spec,
        opt_state,
        param_specs,
        transform_non_params=lambda _: P(),
        is_leaf=lambda x: isinstance(x, P),
    )
