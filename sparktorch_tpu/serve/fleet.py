"""Sharded parameter-server fleet with per-tensor delta pulls.

The hogwild topology's scaling bottleneck is ONE process serving
full-state pulls to every worker (the reference's Flask server on the
driver, ``server.py:33-149`` — aggregate pull bandwidth capped by a
single socket loop no matter how many chips train). This module is
the production shape from Li et al.'s parameter-server work
(OSDI '14): the tensor tree **hash-partitioned across N server
shards** by consistent hashing over leaf paths
(:class:`~sparktorch_tpu.net.sharded.HashRing` — both sides of the
wire compute the same owner from the shard-id list alone), each shard
an independent apply loop + HTTP frontend, so pull bandwidth and
apply throughput scale with shard count.

Per-tensor versioning makes pulls DELTAS: each shard's canonical
leaves live in a :class:`~sparktorch_tpu.utils.locks.TreeVersionedSlot`
(a version tag per leaf beside the global version), and the
``/delta.bin`` route ships only leaves whose tag advanced past the
client's ``X-Have-Version`` — on a sparse-update workload that is a
strict subset of the tree every pull. ``X-Pull-Quant: int8`` further
halves the dominant direction: leaves are served int8 with ONE
per-(leaf, version) quantization shared by every puller and a
server-side error-feedback residual folded into the next version's
quantization (the pull-direction mirror of Lin et al.'s Deep Gradient
Compression, already proven on the push path).

Live resharding: :meth:`ParamServerFleet.add_shard` and
:meth:`~ParamServerFleet.drain_shard` move only the consistent-hash
arcs that changed (~1/N of the leaves) — parameters AND their
per-leaf optimizer states migrate, the ring version bumps, and
clients refresh from any shard's ``/fleet.json``. A shard whose
frontend dies is restarted by the fleet monitor (counted, not fatal);
clients degrade for a grace window in the meantime
(:class:`~sparktorch_tpu.net.sharded.ShardedTransport`).

Mixed-version gangs keep working: the fleet's GATEWAY is a stock
:class:`~sparktorch_tpu.serve.param_server.ParamServerHttp` over a
facade that assembles the full tree across shards and scatters pushed
gradients by ring ownership — dill and binary-v1 workers talk to it
exactly as they talked to the single server.

Optimizer note: shards run the optimizer PER LEAF (each tensor owns
its optax state), which is exact for element-wise optimizers (sgd,
adam, rmsprop — everything the registry serves). A transform that
couples leaves (global-norm clipping) would see per-shard norms
instead; pick the single server for those.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from typing import Any, Dict, List, Mapping, Optional, Tuple

import jax
import numpy as np

from sparktorch_tpu.net import wire as binwire
from sparktorch_tpu.net.sharded import _RING_REPLICAS, HashRing
from sparktorch_tpu.obs import Telemetry, wall_ts
from sparktorch_tpu.serve.param_server import (
    MAX_TOLERATED_ERRORS,
    ParamServerHttp,
)
from sparktorch_tpu.utils.early_stopper import EarlyStopping
from sparktorch_tpu.utils.locks import TreeVersionedSlot
from sparktorch_tpu.utils.serde import ModelSpec, deserialize_model

Path = Tuple[str, ...]


class ShardStopped(RuntimeError):
    """Push enqueued on a shard whose writer has exited (drained or
    failed) — callers must re-route against the current ring instead
    of waiting out an apply that will never come."""


class _LossVote:
    """The fleet-wide windowed early-stop vote (``server.py:102-123``
    parity, shared by every shard so the designated vote shard and the
    gateway agree on one stop decision)."""

    def __init__(self, window_len: int = 3, patience: int = -1):
        self.window_len = max(1, window_len)
        self._stopper = (EarlyStopping(patience=patience)
                         if patience and patience > 0 else None)
        self._losses: List[float] = []
        self._stop = False
        self._lock = threading.Lock()

    def post(self, loss: float) -> bool:
        with self._lock:
            if self._stop:
                return True
            if self._stopper is None:
                return False
            self._losses.append(float(loss))
            if len(self._losses) >= self.window_len:
                avg = float(np.mean(self._losses))
                self._losses.clear()
                if self._stopper.step(avg):
                    self._stop = True
        return self._stop

    @property
    def should_stop(self) -> bool:
        return self._stop


def _render_leaf_body(owner, items, version: int, quant: Optional[str],
                      run_tag: int) -> bytes:
    """Shared encode tail of BOTH delta renderers (shard + gateway —
    one implementation so an EF or cache fix can't land on one path
    and leave the other serving divergent bytes): int8 server-side
    error feedback — each (leaf, cache_tag) quantized ONCE, the
    residual folded into that leaf's next version — then one v2
    frame. ``items``: (path, cache_tag, leaf_version, host_array);
    the caller holds its render lock (the residuals and quant cache
    are owner state)."""
    leaves: List[Tuple[Path, Any]] = []
    leaf_versions: Dict[Path, int] = {}
    for path, cache_tag, lver, arr in items:
        if quant == "int8" and binwire._is_float(arr) and arr.size:
            qc = owner._quant_cache.get(path)
            if qc is None or qc[0] != cache_tag:
                qleaf, residual = binwire.quantize_leaf_int8(
                    arr, owner._pull_residuals.get(path)
                )
                owner._pull_residuals[path] = residual
                owner._quant_cache[path] = (cache_tag, qleaf)
            else:
                qleaf = qc[1]
            leaves.append((path, qleaf))
        else:
            leaves.append((path, arr))
        leaf_versions[path] = lver
    return binwire.frame_bytes(binwire.encode(
        leaves, version=version, run_tag=run_tag,
        leaf_versions=leaf_versions,
    ))


def _body_cache_get(owner, key, version: int):
    """Shared body-cache lookup with ONE eviction rule for both
    renderers: a new version or >64 keys clears the cache. Caller
    holds its render lock."""
    if owner._bodies_version != version or len(owner._bodies) > 64:
        owner._bodies.clear()
        owner._bodies_version = version
    return owner._bodies.get(key)


class ParamShardServer:
    """One fleet shard: the canonical owner of a hash range of leaves.

    Holds its leaves in a :class:`TreeVersionedSlot` (per-leaf version
    tags → delta pulls), applies gradient partials on a single writer
    thread through a per-leaf jitted optimizer update, and renders
    version-2 delta frames with per-version body/quantization caches
    so a worker swarm pulling the same delta shares one render.

    The object satisfies the :class:`ParamServerHttp` server contract
    (``slot`` / ``telemetry`` / ``push_gradients`` / ``post_loss``),
    so a stock HTTP frontend serves it — legacy full-pull routes
    included (they ship the shard's SUBTREE).
    """

    def __init__(self, shard_id, leaves: Mapping[Path, Any],
                 make_tx, device: Optional[jax.Device] = None,
                 telemetry: Optional[Telemetry] = None,
                 loss_vote: Optional[_LossVote] = None):
        self.shard_id = str(shard_id)
        self.device = device or jax.devices()[0]
        self.telemetry = telemetry or Telemetry(
            run_id=f"param_shard_{self.shard_id}"
        )
        self._labels = {"shard": self.shard_id}
        self._loss_vote = loss_vote or _LossVote()
        self._tx = make_tx()
        placed = {tuple(p): jax.device_put(v, self.device)
                  for p, v in leaves.items()}
        self.slot = TreeVersionedSlot(placed)
        self._opt: Dict[Path, Any] = {
            p: jax.device_put(self._tx.init(v), self.device)
            for p, v in placed.items()
        }

        def _apply(params, opt_states, grads):
            """One fused update over a PARTIAL leaf dict: every pushed
            leaf updates in a single dispatch (a per-leaf loop would
            cost one GIL-holding dispatch per tensor per push — the
            apply path must scale with pushes, not leaves). Each leaf
            still owns its optax state, so the math equals the
            per-leaf form for element-wise optimizers."""
            import optax

            grads = {k: g.astype(params[k].dtype) for k, g in grads.items()}
            new_params: Dict[str, Any] = {}
            new_opts: Dict[str, Any] = {}
            for k in grads:
                updates, new_opts[k] = self._tx.update(
                    grads[k], opt_states[k], params[k]
                )
                new_params[k] = optax.apply_updates(params[k], updates)
            return new_params, new_opts

        # Jit cache keys on the dict's key-set + shapes: a stable push
        # pattern (full tree, or a stable sparse subset) compiles once.
        self._apply_fn = jax.jit(_apply)

        # Render caches (all guarded by _render_lock): host copies per
        # (path, leaf_version), int8 quantizations per (path, leaf
        # version) with the shared error-feedback residuals, and whole
        # delta BODIES per (version, have, quant) — a swarm pulling
        # the same delta pays one encode.
        self._render_lock = threading.Lock()
        self._host_leaves: Dict[Path, Tuple[int, np.ndarray]] = {}
        self._quant_cache: Dict[Path, Tuple[int, binwire.QuantLeaf]] = {}
        self._pull_residuals: Dict[Path, np.ndarray] = {}
        self._bodies: Dict[Tuple, bytes] = {}
        self._bodies_version: Optional[int] = None

        self._state_lock = threading.Lock()
        # Serializes the running-check-then-enqueue against stop()'s
        # drain: without it a push slipping between the check and the
        # put lands on a queue nobody will ever service and its
        # wait=True caller sits out the full timeout.
        self._enqueue_lock = threading.Lock()
        self._queue: "queue.Queue" = queue.Queue()
        self._errors = 0
        self._failed: Optional[BaseException] = None
        self._applied = 0
        self._misrouted = 0
        self._running = True
        self._writer = threading.Thread(target=self._apply_loop, daemon=True)
        self._writer.start()

    # -- gradient path -----------------------------------------------------

    def push_gradients(self, grads, wait: bool = True,
                       timeout: float = 60.0,
                       trace_ctx=None) -> threading.Event:
        """Enqueue a gradient PARTIAL (nested subtree or ``{path:
        array}``) for the writer thread; same wait/FIFO semantics as
        the single server. Returns the apply-completion event either
        way, so a scatter caller can enqueue on every shard FIRST and
        wait on the events together (latency = max of shard applies,
        not their sum). ``trace_ctx`` rides the queue item so the
        writer thread attributes this request's queue-wait and apply
        as child spans — the single-writer queue is exactly where
        sharded p99 hides."""
        if self._failed is not None:
            raise RuntimeError(
                f"param shard {self.shard_id} failed"
            ) from self._failed
        if isinstance(grads, Mapping) and any(
            isinstance(k, tuple) for k in grads
        ):
            flat = {tuple(p): g for p, g in grads.items()}
        else:
            flat = dict(binwire.flatten_tree(grads))
        done = threading.Event()
        with self._enqueue_lock:
            if not self._running:
                # Fast-fail instead of letting wait=True sit out its
                # full timeout on a queue nobody drains (the shard was
                # drained or stopped between the caller's ring
                # snapshot and now). Checked under the enqueue lock so
                # a put can never slip past stop()'s final drain.
                raise ShardStopped(
                    f"param shard {self.shard_id} is stopped"
                )
            self._queue.put((flat, done, trace_ctx,
                             wall_ts(), time.perf_counter()))  # lint-obs: ok (request-latency histogram clock pair, not a ledger region)
        self.telemetry.counter("param_server.pushes", labels=self._labels)
        if wait and not done.wait(timeout):
            raise TimeoutError(
                f"param shard {self.shard_id} apply timed out"
            )
        return done

    def _apply_loop(self) -> None:
        from sparktorch_tpu.obs.rpctrace import tracer_for

        tracer = tracer_for(self.telemetry)
        while self._running:
            try:
                flat, done, tctx, enq_ts, enq_t0 = self._queue.get(
                    timeout=0.1)
            except queue.Empty:
                continue
            try:
                t0 = time.perf_counter()  # lint-obs: ok (request-latency histogram clock pair, not a ledger region)
                tracer.record("queue_wait", tctx, enq_ts, t0 - enq_t0,
                              kind="server", shard=self.shard_id)
                # Stage H2D transfers BEFORE taking the state lock
                # (sparklint SPK301): pulls must not wait on device
                # transfer. A leaf misrouted by a stale ring pays one
                # wasted transfer — rare, counted, self-healing.
                staged = {path: jax.device_put(np.asarray(grad),
                                               self.device)
                          for path, grad in flat.items()}
                with tracer.child_span("apply", tctx, kind="server",
                                       shard=self.shard_id), \
                        self._state_lock:
                    _version, params, _vers = self.slot.read_leaves()
                    owned: Dict[str, Path] = {}
                    grads: Dict[str, Any] = {}
                    for path, dev_grad in staged.items():
                        if path not in params:
                            # A partial routed by a stale ring (leaf
                            # moved by add/drain): dropped + counted,
                            # the client's next ring refresh fixes it.
                            self._misrouted += 1
                            self.telemetry.counter(
                                "fleet.misrouted_leaves_total",
                                labels=self._labels)
                            continue
                        key = "/".join(path)
                        owned[key] = path
                        grads[key] = dev_grad
                    if owned:
                        new_params, new_opts = self._apply_fn(
                            {k: params[p] for k, p in owned.items()},
                            {k: self._opt[p] for k, p in owned.items()},
                            grads,
                        )
                        for key, path in owned.items():
                            self._opt[path] = new_opts[key]
                        self.slot.swap_leaves(
                            {path: new_params[key]
                             for key, path in owned.items()}
                        )
                        self._applied += 1
                        self.telemetry.counter("param_server.applies",
                                               labels=self._labels)
                self.telemetry.observe("param_server.apply_s",
                                       time.perf_counter() - t0,  # lint-obs: ok (request-latency histogram clock pair, not a ledger region)
                                       labels=self._labels)
                self.telemetry.gauge("param_server.version",
                                     self.slot.version, labels=self._labels)
            except Exception as e:
                self._errors += 1
                self.telemetry.counter("param_server.apply_errors",
                                       labels=self._labels)
                if self._errors > MAX_TOLERATED_ERRORS:
                    self._failed = e
                    self._running = False
            finally:
                if done is not None:
                    done.set()
                self._queue.task_done()

    def drain(self, timeout: float = 30.0) -> None:
        deadline = time.monotonic() + timeout
        while self._queue.unfinished_tasks and time.monotonic() < deadline:
            time.sleep(0.005)

    @property
    def applied_updates(self) -> int:
        return self._applied

    # -- delta rendering ---------------------------------------------------

    def render_delta(self, have_version: int, quant: Optional[str] = None,
                     run_tag: int = 0) -> Tuple[int, Optional[bytes]]:
        """``(version, body)`` — a v2 delta frame of every leaf whose
        version advanced past ``have_version``; ``(version, None)``
        when the client is up to date (the route's 304).

        ``quant='int8'`` serves int8 leaves with server-side error
        feedback: each (leaf, version) is quantized ONCE — every
        client pulling that version gets identical bytes and the
        residual is consumed exactly once — and the residual is added
        before quantizing the leaf's next version, so compression
        noise averages out across served versions instead of
        accumulating as bias.
        """
        if quant not in (None, "", "int8"):
            raise ValueError(f"pull quant {quant!r}; use int8 or nothing")
        have = int(have_version)
        self.telemetry.counter("fleet.delta_pulls", labels=self._labels)
        delta = self.slot.read_delta(have)
        if delta is None:
            return self.slot.version, None
        version, entries = delta
        # Cache key = the (path, leaf_version) SET the delta contains,
        # not the client's raw have-version: a swarm whose members sit
        # at different have values usually selects the SAME leaf set,
        # and must share one render (the single server shares one body
        # per version; the fleet must not regress to per-client
        # encodes under swarm load).
        key = (version, quant or "",
               tuple(sorted((p, v) for p, _, v in entries)))
        with self._render_lock:
            body = _body_cache_get(self, key, version)
            if body is not None:
                return version, body
            items = []
            for path, leaf, lver in entries:
                cached = self._host_leaves.get(path)
                if cached is None or cached[0] != lver:
                    arr = np.asarray(leaf)
                    self._host_leaves[path] = (lver, arr)
                else:
                    arr = cached[1]
                items.append((path, lver, lver, arr))
            body = _render_leaf_body(self, items, version, quant,
                                     run_tag)
            self._bodies[key] = body
            self.telemetry.counter("fleet.delta_renders",
                                   labels=self._labels)
            return version, body

    # -- live resharding ---------------------------------------------------

    def extract(self, paths) -> Dict[Path, Dict[str, Any]]:
        """Atomically remove ``paths`` (params + their optimizer
        states) for migration to another shard. The writer thread
        can't interleave: it applies under the same state lock."""
        with self._state_lock, self._render_lock:
            removed = self.slot.remove_leaves(paths)
            out: Dict[Path, Dict[str, Any]] = {}
            for path, leaf in removed.items():
                out[path] = {"param": leaf, "opt": self._opt.pop(path, None)}
                self._host_leaves.pop(path, None)
                self._quant_cache.pop(path, None)
                self._pull_residuals.pop(path, None)
            self._bodies.clear()
            self._bodies_version = None
            return out

    def install(self, entries: Mapping[Path, Mapping[str, Any]]) -> None:
        """Adopt migrated leaves: params + optimizer states land on
        this shard's device, stamped with a fresh version so every
        delta client picks them up on its next pull."""
        if not entries:
            return
        # Stage device transfers OUTSIDE the state lock (sparklint
        # SPK301): entries are the caller's migration payload, so only
        # the _opt/slot swap needs pull-consistency.
        staged = []
        for path, entry in entries.items():
            path = tuple(path)
            param = jax.device_put(entry["param"], self.device)
            opt = entry.get("opt")
            opt_state = (jax.device_put(opt, self.device)
                         if opt is not None else self._tx.init(param))
            staged.append((path, param, opt_state))
        with self._state_lock:
            new_leaves: Dict[Path, Any] = {}
            for path, param, opt_state in staged:
                self._opt[path] = opt_state
                new_leaves[path] = param
            self.slot.swap_leaves(new_leaves)

    # -- early stopping / lifecycle ----------------------------------------

    def post_loss(self, loss: float) -> bool:
        self.telemetry.counter("param_server.losses_posted",
                               labels=self._labels)
        return self._loss_vote.post(loss)

    @property
    def should_stop(self) -> bool:
        return self._loss_vote.should_stop

    def stop(self) -> None:
        self._running = False
        if self._writer.is_alive():
            self._writer.join(timeout=5.0)
        # Release any pusher that enqueued before the flag flipped:
        # its gradient is lost (the shard is gone), but a wait=True
        # caller must not sit out its full timeout on an unserviced
        # event. Under the enqueue lock, so no put can land AFTER this
        # drain (push_gradients re-checks _running under the same
        # lock and fast-fails).
        with self._enqueue_lock:
            while True:
                try:
                    _flat, done = self._queue.get_nowait()[:2]
                except queue.Empty:
                    break
                if done is not None:
                    done.set()
                self._queue.task_done()


# ---------------------------------------------------------------------------
# Gateway facade: the single-server wire over the whole fleet
# ---------------------------------------------------------------------------


class _CompositeSlot:
    """A read-only VersionedSlot view assembling the full tree across
    shards. The composite version is the SUM of shard versions plus
    the fleet's drain offset — monotonic through applies, adds, and
    drains, so legacy ``X-Have-Version`` 204/304 semantics hold."""

    def __init__(self, fleet: "ParamServerFleet"):
        self._fleet = fleet
        # Boot nonce for the gateway's delta route (same contract as
        # TreeVersionedSlot.epoch): a REBUILT gateway restarts its
        # composite-version stamping, and clients detect that by epoch
        # change, never by version arithmetic.
        self.epoch = int.from_bytes(os.urandom(8), "little") >> 1

    def read(self) -> Tuple[int, Any]:
        # Under the topology lock: mid-drain, the offset and the shard
        # map change in two steps, and reading between them would
        # double-count the drained shard's versions — a legacy client
        # would store the inflated value as its have-version and then
        # 304 through the next V real updates. Contention is only
        # against add/drain (rare); applies never hold this lock.
        with self._fleet._topology_lock:
            version = self._fleet._version_offset
            flat: Dict[Path, Any] = {}
            for shard in self._fleet._shards.values():
                v, leaves, _ = shard.slot.read_leaves()
                version += v
                flat.update(leaves)
        return version, binwire.unflatten_tree(list(flat.items()))

    @property
    def version(self) -> int:
        with self._fleet._topology_lock:
            return self._fleet._version_offset + sum(
                s.slot.version for s in self._fleet._shards.values()
            )


class _GatewayFacade:
    """Duck-types the :class:`ParameterServer` surface
    :class:`ParamServerHttp` serves, backed by the whole fleet:
    pulls assemble, pushes scatter by ring ownership — and
    ``render_delta`` assembles the per-shard v2 DELTA state into one
    frame, so legacy-topology clients (and serving replicas pointed at
    a gateway) get the per-tensor delta byte win without speaking the
    ring."""

    def __init__(self, fleet: "ParamServerFleet"):
        self._fleet = fleet
        self.slot = _CompositeSlot(fleet)
        self.telemetry = fleet.telemetry
        # Delta assembly state (all under _render_lock). Per-shard
        # leaf versions are NOT comparable across shards (independent
        # counters), so the gateway re-stamps every observed
        # (shard, leaf_version) change with the COMPOSITE version
        # current at observation — monotonic by construction of
        # _CompositeSlot.version — and serves "every leaf whose
        # composite stamp advanced past the client's have".
        self._render_lock = threading.Lock()
        self._stamp: Dict[Path, Tuple[str, int]] = {}
        self._cstamp: Dict[Path, int] = {}
        self._host_leaves: Dict[Path, Tuple[Tuple[str, int],
                                            np.ndarray]] = {}
        self._quant_cache: Dict[Path, Tuple[Tuple[str, int],
                                            binwire.QuantLeaf]] = {}
        self._pull_residuals: Dict[Path, np.ndarray] = {}
        self._bodies: Dict[Tuple, bytes] = {}
        self._bodies_version: Optional[int] = None
        self._last_walk_sig: Optional[Tuple] = None

    def render_delta(self, have_version: int, quant: Optional[str] = None,
                     run_tag: int = 0) -> Tuple[int, Optional[bytes]]:
        """``(composite_version, body)`` — one v2 delta frame of every
        leaf (from ANY shard) whose state changed past the client's
        composite ``have_version``; ``(version, None)`` when up to
        date. Same int8 server-side error-feedback and shared-render
        caching contract as :meth:`ParamShardServer.render_delta`,
        with the gateway owning its own residuals (it serves its own
        quantized stream). A composite version that advanced with no
        leaf change (an empty shard drained) answers 304 — correct,
        just conservative."""
        if quant not in (None, "", "int8"):
            raise ValueError(f"pull quant {quant!r}; use int8 or nothing")
        have = int(have_version)
        self.telemetry.counter("fleet.gateway_delta_pulls")
        with self._fleet._topology_lock:
            # One coherent topology read (see _CompositeSlot.read for
            # why the lock matters mid-drain); the per-shard slot
            # reads inside are lock-free snapshots.
            version = self._fleet._version_offset
            shard_reads = []
            for shard in self._fleet._shards.values():
                v, leaves, vers = shard.slot.read_leaves()
                version += v
                shard_reads.append((shard.shard_id, v, leaves, vers))
        with self._render_lock:
            # Steady-state fast path: N replicas each poll at 20Hz,
            # and when no shard's slot version moved since the last
            # render the stamps are already current — skip the
            # O(total_leaves) restamp walk straight to the 304/cached
            # answer. An OLDER concurrent read may regress the
            # signature (forcing one redundant walk next poll); the
            # per-leaf guards below keep that harmless.
            sig = tuple(sorted((sid, v) for sid, v, _, _ in shard_reads))
            if sig != self._last_walk_sig:
                for sid, _v, leaves, vers in shard_reads:
                    for path, lver in vers.items():
                        tag = (sid, lver)
                        # Strict version guard: two concurrent renders
                        # serialize HERE after reading the topology at
                        # different instants, and the older read must
                        # not re-stamp a leaf backwards (it would 304
                        # newer state to clients until the next real
                        # change). Genuine changes always advance the
                        # composite version, so older-read
                        # observations lose.
                        if self._stamp.get(path) != tag \
                                and version > self._cstamp.get(path, -1):
                            self._stamp[path] = tag
                            self._cstamp[path] = version
                            self._host_leaves[path] = (tag, np.asarray(
                                leaves[path]))
                self._last_walk_sig = sig
            if have >= version:
                return version, None
            changed = [p for p, cv in self._cstamp.items() if cv > have]
            if not changed:
                return version, None
            key = (version, quant or "",
                   tuple(sorted((p, self._cstamp[p]) for p in changed)))
            body = _body_cache_get(self, key, version)
            if body is not None:
                return version, body
            items = []
            for path in changed:
                tag, arr = self._host_leaves[path]
                items.append((path, tag, self._cstamp[path], arr))
            body = _render_leaf_body(self, items, version, quant,
                                     run_tag)
            self._bodies[key] = body
            self.telemetry.counter("fleet.gateway_delta_renders")
            return version, body

    def push_gradients(self, grads, wait: bool = True,
                       timeout: float = 60.0, trace_ctx=None) -> None:
        self._fleet.scatter_push(grads, wait=wait, timeout=timeout,
                                 trace_ctx=trace_ctx)

    def post_loss(self, loss: float) -> bool:
        return self._fleet.post_loss(loss)


# ---------------------------------------------------------------------------
# The fleet
# ---------------------------------------------------------------------------


class ParamServerFleet:
    """N param-server shards + gateway + restart monitor, presented
    through the same driver-side surface as :class:`ParameterServer`
    (``model_state`` / ``final_state`` / ``should_stop`` /
    ``applied_updates`` / ``stop``), so ``train_async(shards=N)``
    swaps it in without touching the worker loop.
    """

    def __init__(self, torch_obj, n_shards: int = 2,
                 window_len: int = 3, early_stop_patience: int = -1,
                 seed: int = 0, telemetry: Optional[Telemetry] = None,
                 devices: Optional[List[jax.Device]] = None,
                 ring_replicas: int = _RING_REPLICAS,
                 restart_shards: bool = True):
        if n_shards < 1:
            raise ValueError(f"n_shards must be >= 1, got {n_shards}")
        self.spec: ModelSpec = deserialize_model(torch_obj)
        self.telemetry = telemetry or Telemetry(run_id="param_fleet")
        self._devices = list(devices or jax.devices())
        self._loss_vote = _LossVote(window_len, early_stop_patience)
        self.restart_shards = restart_shards

        # One deterministic init (same contract as ParameterServer:
        # the server owns the canonical init), then partition the leaf
        # paths across the ring.
        rng = jax.random.key(seed)
        variables = dict(self.spec.init_params(rng))
        params = variables.pop("params", variables)
        self._model_state = variables
        flat = dict(binwire.flatten_tree(
            jax.tree.map(lambda a: np.asarray(a), params)
        ))

        self.ring = HashRing(range(n_shards), replicas=ring_replicas)
        self.ring_version = 1
        self._version_offset = 0  # keeps the gateway version monotonic
        # across drains (a drained shard's versions leave the sum)
        assignment = self.ring.assignment(flat)
        self._shards: Dict[str, ParamShardServer] = {}
        for i, sid in enumerate(self.ring.shard_ids):
            self._shards[sid] = ParamShardServer(
                sid, {p: flat[p] for p in assignment[sid]},
                make_tx=self.spec.make_optimizer,
                device=self._devices[i % len(self._devices)],
                telemetry=self.telemetry, loss_vote=self._loss_vote,
            )
        self.telemetry.gauge("fleet.shards", len(self._shards))

        self._https: Dict[str, ParamServerHttp] = {}
        self._gateway: Optional[ParamServerHttp] = None
        self._desired: set = set()
        self._death_noticed: Dict[str, float] = {}
        self._topology_lock = threading.RLock()
        self._monitor_stop = threading.Event()
        self._monitor: Optional[threading.Thread] = None
        self._host = "127.0.0.1"

    # -- topology ----------------------------------------------------------

    def describe(self) -> Dict[str, Any]:
        """The ``/fleet.json`` document clients build their ring
        from — served by every shard and the gateway. Under the
        topology lock (reentrant): it is called from handler threads
        mid-add/drain, and a torn read would pair the old ring
        version with the new shard map (or die iterating a mutating
        dict)."""
        with self._topology_lock:
            return {
                "run_id": self.telemetry.run_id,
                "ring_version": self.ring_version,
                "replicas": self.ring.replicas,
                "shards": self.urls(),
                "gateway": self._gateway.url if self._gateway else None,
            }

    def urls(self) -> Dict[str, str]:
        with self._topology_lock:
            return {sid: http.url for sid, http in self._https.items()}

    @property
    def gateway_url(self) -> str:
        if self._gateway is None:
            raise RuntimeError("fleet not started")
        return self._gateway.url

    def collector_targets(self, per_shard: bool = False) -> Dict[str, str]:
        """Fleet-aware :class:`~sparktorch_tpu.obs.collector.
        FleetCollector` targets.

        Default: ONE target (the gateway, falling back to the first
        shard) — this in-process fleet runs every shard on the SAME
        telemetry bus, so every frontend serves the identical
        snapshot and scraping each would duplicate every series once
        per target in the merged view (per-shard attribution already
        rides the series' own ``shard`` labels). ``per_shard=True``
        opts into one target per frontend — the right shape once
        shards are separate processes with their own buses (ROADMAP
        follow-up)."""
        with self._topology_lock:
            if per_shard:
                targets = {f"shard{sid}": url
                           for sid, url in self.urls().items()}
                if self._gateway is not None:
                    targets["gateway"] = self._gateway.url
                return targets
            if self._gateway is not None:
                return {"fleet": self._gateway.url}
            urls = self.urls()
            sid = sorted(urls)[0]
            return {"fleet": urls[sid]}

    def _start_shard_http(self, sid: str, port: int = 0) -> ParamServerHttp:
        return ParamServerHttp(
            self._shards[sid], host=self._host, port=port, shard=sid,
            extra_json_routes={"/fleet.json": self.describe},
            ring_version_fn=lambda: self.ring_version,
        ).start()

    def start(self, host: str = "127.0.0.1", port: int = 0,
              gateway: bool = True) -> "ParamServerFleet":
        """Start every shard frontend (ephemeral ports), the legacy
        gateway on ``port``, and the restart monitor."""
        self._host = host
        with self._topology_lock:
            for sid in self.ring.shard_ids:
                if sid not in self._https:
                    self._https[sid] = self._start_shard_http(sid)
                    self._desired.add(sid)
            if gateway and self._gateway is None:
                self._gateway = ParamServerHttp(
                    _GatewayFacade(self), host=host, port=port,
                    extra_json_routes={"/fleet.json": self.describe},
                    ring_version_fn=lambda: self.ring_version,
                ).start()
        if self.restart_shards and self._monitor is None:
            self._monitor_stop.clear()
            self._monitor = threading.Thread(
                target=self._monitor_loop, daemon=True,
                name="fleet-monitor",
            )
            self._monitor.start()
        return self

    def _monitor_loop(self) -> None:
        """Shard-death degradation: a dead shard FRONTEND (chaos kill,
        handler crash) is restarted on its old port — counted in
        ``fleet.shard_restarts_total`` and timed in
        ``fleet.shard_recovery_latency_s`` — well inside the clients'
        grace window, so a seeded kill costs staleness, never the
        run."""
        while not self._monitor_stop.wait(0.05):
            with self._topology_lock:
                dead = [
                    (sid, http) for sid, http in self._https.items()
                    if sid in self._desired and http._httpd is None
                ]
            for sid, http in dead:
                now = time.monotonic()
                self._death_noticed.setdefault(sid, now)
                try:
                    new = self._start_shard_http(sid, port=http.port)
                except OSError:
                    continue  # port in TIME_WAIT; retry next tick
                with self._topology_lock:
                    if sid in self._desired:
                        self._https[sid] = new
                        self.telemetry.counter(
                            "fleet.shard_restarts_total",
                            labels={"shard": sid})
                        self.telemetry.observe(
                            "fleet.shard_recovery_latency_s",
                            time.monotonic()
                            - self._death_noticed.pop(sid))
                    else:
                        new.stop()  # drained while restarting

    def kill_shard(self, shard_id) -> None:
        """Take one shard's HTTP frontend down WITHOUT draining it —
        the fault-injection surface (`ft.chaos` uses the same path via
        the ``fleet.shard`` site). The monitor restarts it."""
        self._https[str(shard_id)].stop()

    def add_shard(self, device: Optional[jax.Device] = None) -> str:
        """Grow the ring live: a new shard joins, and ONLY the leaves
        whose consistent-hash arc moved migrate to it (params +
        optimizer state). Returns the new shard id."""
        with self._topology_lock:
            sid = str(max((int(s) for s in self._shards), default=-1) + 1)
            shard = ParamShardServer(
                sid, {}, make_tx=self.spec.make_optimizer,
                device=device or self._devices[
                    len(self._shards) % len(self._devices)],
                telemetry=self.telemetry, loss_vote=self._loss_vote,
            )
            self.ring.add(sid)
            moved: Dict[Path, Dict[str, Any]] = {}
            for other in self._shards.values():
                other.drain()
                mine = [p for p in other.slot.paths
                        if self.ring.owner(p) == sid]
                if mine:
                    moved.update(other.extract(mine))
            shard.install(moved)
            self._shards[sid] = shard
            if self._https:  # started fleet: serve the new shard now
                self._https[sid] = self._start_shard_http(sid)
                self._desired.add(sid)
            self.ring_version += 1
            self.telemetry.gauge("fleet.shards", len(self._shards))
            self.telemetry.counter("fleet.reshards_total",
                                   labels={"op": "add"})
            self.telemetry.counter("fleet.leaves_moved_total", len(moved),
                                   labels={"op": "add"})
            return sid

    def drain_shard(self, shard_id) -> int:
        """Shrink the ring live: the shard's leaves (params +
        optimizer states) migrate to their new consistent-hash owners,
        then the shard stops. Returns the number of leaves moved."""
        sid = str(shard_id)
        with self._topology_lock:
            if len(self._shards) <= 1:
                raise ValueError("cannot drain the last shard")
            shard = self._shards[sid]
            self.ring.remove(sid)
            self._desired.discard(sid)
            shard.drain()
            entries = shard.extract(shard.slot.paths)
            groups: Dict[str, Dict[Path, Any]] = {}
            for path, entry in entries.items():
                groups.setdefault(self.ring.owner(path), {})[path] = entry
            for target_sid, part in groups.items():
                self._shards[target_sid].install(part)
            # Keep the gateway's composite version monotonic: the
            # drained shard's count leaves the sum for good.
            self._version_offset += shard.slot.version
            http = self._https.pop(sid, None)
            if http is not None:
                http.stop()
            del self._shards[sid]
            shard.stop()
            self.ring_version += 1
            self.telemetry.gauge("fleet.shards", len(self._shards))
            self.telemetry.counter("fleet.reshards_total",
                                   labels={"op": "drain"})
            self.telemetry.counter("fleet.leaves_moved_total",
                                   len(entries), labels={"op": "drain"})
            return len(entries)

    # -- driver-side ParameterServer surface -------------------------------

    def scatter_push(self, grads, wait: bool = True,
                     timeout: float = 60.0, trace_ctx=None) -> None:
        """Split a gradient tree (nested, or flat ``{path: array}`` —
        partials welcome) by ring ownership and push each piece to its
        shard (the gateway's legacy-push path). A shard drained
        between the ring snapshot and the push fast-fails with
        :class:`ShardStopped`; the partial re-routes once against the
        refreshed ring (its leaves moved with the drain).
        ``trace_ctx`` (the gateway serve span's context) fans out to
        every shard writer, whose queue-wait/apply spans come back
        annotated with their shard id — the gateway hop of a traced
        legacy push stays attributable."""
        if isinstance(grads, Mapping) and any(
            isinstance(k, tuple) for k in grads
        ):
            flat = {tuple(p): g for p, g in grads.items()}
        else:
            flat = dict(binwire.flatten_tree(grads))
        pending = set(flat)
        events: List[Tuple[str, threading.Event]] = []
        for attempt in range(2):
            with self._topology_lock:
                groups = self.ring.assignment(pending)
                shards = dict(self._shards)
            try:
                # Two-phase: ENQUEUE on every shard first (the applies
                # run in parallel on the shard writer threads), wait
                # after — one scatter costs the slowest shard's apply,
                # not the sum of all of them.
                for sid, paths in groups.items():
                    if paths:
                        events.append((sid, shards[sid].push_gradients(
                            {p: flat[p] for p in paths}, wait=False,
                            timeout=timeout, trace_ctx=trace_ctx,
                        )))
                        # Only landed partials leave the retry set — a
                        # blind full retry would double-apply on the
                        # shards that already took theirs.
                        pending.difference_update(paths)
                break
            except ShardStopped:
                if attempt:
                    raise
                self.telemetry.counter("fleet.push_reroutes_total")
        if wait:
            deadline = time.monotonic() + timeout
            for sid, event in events:
                if not event.wait(max(0.0, deadline - time.monotonic())):
                    raise TimeoutError(
                        f"param shard {sid} apply timed out"
                    )

    def post_loss(self, loss: float) -> bool:
        return self._loss_vote.post(loss)

    @property
    def should_stop(self) -> bool:
        return self._loss_vote.should_stop

    @property
    def applied_updates(self) -> int:
        with self._topology_lock:
            return sum(s.applied_updates for s in self._shards.values())

    def model_state(self):
        return self._model_state

    def drain(self, timeout: float = 30.0) -> None:
        with self._topology_lock:
            shards = list(self._shards.values())
        for shard in shards:
            shard.drain(timeout=timeout)

    def assemble(self) -> Any:
        """The full parameter tree across every shard (leaves stay on
        their shard devices)."""
        with self._topology_lock:
            shards = list(self._shards.values())
        flat: Dict[Path, Any] = {}
        for shard in shards:
            _v, leaves, _vers = shard.slot.read_leaves()
            flat.update(leaves)
        return binwire.unflatten_tree(list(flat.items()))

    def final_state(self):
        self.drain()
        return self.assemble(), self._model_state

    def stop(self) -> None:
        self._monitor_stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout=5.0)
            self._monitor = None
        with self._topology_lock:
            self._desired.clear()
            for http in self._https.values():
                http.stop()
            self._https.clear()
            if self._gateway is not None:
                self._gateway.stop()
                self._gateway = None
        for shard in self._shards.values():
            shard.stop()


# ---------------------------------------------------------------------------
# Process entry point
# ---------------------------------------------------------------------------


def run_shard_server(torch_obj, shard_id, n_shards: int,
                     seed: int = 0, host: str = "127.0.0.1",
                     port: int = 0, window_len: int = 3,
                     early_stop_patience: int = -1,
                     ring_replicas: int = _RING_REPLICAS,
                     heartbeat_interval_s: float = 1.0,
                     url_path: Optional[str] = None,
                     ctx=None) -> Dict[str, Any]:
    """ONE fleet shard as a standalone process — the entry-point shape
    the ROADMAP filed ("shard servers as real processes/hosts"),
    runnable under ``python -m sparktorch_tpu.ctl.worker`` with
    ``kind='shard_server'`` (the elastic control plane's spawn path).

    Determinism replaces coordination: every shard process derives the
    SAME full tree from ``(torch_obj, seed)`` and the same ring from
    ``(n_shards, ring_replicas)``, then keeps only its own hash range
    — no driver-side hand-off of tensors, exactly how clients compute
    leaf ownership from ``/fleet.json`` alone. Serves the stock shard
    frontend (binary v1/v2 + delta routes) on ``host:port`` until the
    context's cancel event fires (SIGTERM under the ctl entry), then
    drains the writer queue and stops. ``url_path`` (or the ctl
    context's heartbeat) publishes the bound URL for discovery.
    """
    spec = deserialize_model(torch_obj)
    rng = jax.random.key(seed)
    variables = dict(spec.init_params(rng))
    params = variables.pop("params", variables)
    flat = dict(binwire.flatten_tree(
        jax.tree.map(lambda a: np.asarray(a), params)))
    ring = HashRing(range(int(n_shards)), replicas=ring_replicas)
    own = ring.assignment(flat).get(str(shard_id), [])
    telemetry = getattr(ctx, "telemetry", None) or Telemetry(
        run_id=f"shard_{shard_id}")
    shard = ParamShardServer(
        shard_id, {p: flat[p] for p in own},
        make_tx=spec.make_optimizer, telemetry=telemetry,
        loss_vote=_LossVote(window_len, early_stop_patience),
    )
    http = ParamServerHttp(shard, host=host, port=port,
                           shard=str(shard_id)).start()
    if url_path:
        tmp = url_path + ".tmp"
        with open(tmp, "w") as f:  # lint-obs: ok (url handoff, not telemetry)
            f.write(http.url)
        os.replace(tmp, url_path)
    cancel = getattr(ctx, "cancel", None) or threading.Event()
    hb = getattr(ctx, "heartbeat", None)
    try:
        while not cancel.wait(heartbeat_interval_s):
            if hb is not None:
                # Liveness + progress: the applied-update count is the
                # shard's "step" for skew/stall readers.
                hb.notify_step(shard.applied_updates)
    finally:
        try:
            shard.drain(timeout=10.0)
        finally:
            http.stop()
            shard.stop()
    return {"shard_id": str(shard_id), "url": http.url,
            "leaves": len(own),
            "applied_updates": shard.applied_updates}
