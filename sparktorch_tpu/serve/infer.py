"""Continuous-batching online inference replica with live weight pulls.

The reference's only serving story is a batch-1 Python UDF per
DataFrame row (``torch_distributed.py:96-127``); this repo's
:class:`~sparktorch_tpu.inference.BatchPredictor` compiled that into
fixed chunks but stayed a single-host BATCH tool — a caller hands it a
matrix and waits. Online traffic is the opposite shape: many small
requests arriving continuously, each with its own latency budget.
This module is the serving half the ROADMAP's "heavy traffic" north
star was missing:

- **Continuous batching** (:class:`InferenceReplica`): requests are
  admitted into a bounded queue and coalesced into the NEXT in-flight
  batch — no fixed windows, no timers. Batches pad up to one of a few
  BUCKET sizes so XLA compiles once per bucket (warmed up front), and
  padded rows are trimmed before results fan back out, so a request
  only ever sees its own rows. Admission is where backpressure lives:
  a full queue answers 429 (:class:`Overloaded`, counted) instead of
  queueing unboundedly, and a request whose deadline lapses while
  queued is expired without wasting a batch slot on it.
- **Live weight updates** (:class:`WeightPuller`): a background thread
  pulls fresh parameters from a parameter server — the binary wire's
  version-tagged 304 pulls against a single server or the fleet
  gateway's ``/delta.bin`` (only advanced leaves ship), or a
  :class:`~sparktorch_tpu.net.sharded.ShardedTransport` against the
  shard fleet — and atomically swaps the serving (params, state) pair
  BETWEEN batches. A hogwild training run and its serving fleet share
  one substrate: the same server, the same wire, the same versions.
- **Observability**: batch fill, queue depth, request latency, and
  batch execution land on the Telemetry bus (``serve.*``); per-rank
  heartbeats give the router its liveness signal; sampled RPC trace
  contexts handed down by the router get ``queue_wait`` and
  ``execute`` child spans, so a slow request's waterfall says whether
  it waited in admission or burned in the batch.

Replicas are thread-hosted like the param-server fleet's shards (one
process, real sockets optional) — the deployment seam for
process-per-replica is the same as the fleet's (ROADMAP follow-up).
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from sparktorch_tpu.ft import chaos as _chaos
from sparktorch_tpu.obs.telemetry import wall_ts
from sparktorch_tpu.net import wire as _wire
from sparktorch_tpu.net.transport import TransportError
from sparktorch_tpu.utils.locks import VersionedSlot

DEFAULT_BUCKETS = (1, 8, 32)


class Overloaded(RuntimeError):
    """Admission refused: the replica's (or router's) queue is full.
    The HTTP spelling is 429 — callers shed load or retry elsewhere."""

    status = 429


class DeadlineExceeded(RuntimeError):
    """The request's deadline lapsed before it reached a batch."""


class ReplicaStopped(RuntimeError):
    """The replica died (chaos kill, stop) with this request pending —
    the router re-routes; a direct caller retries elsewhere."""


class InferFuture:
    """Completion handle for one admitted request. ``result()`` blocks
    until the batch that carried the request lands, then returns this
    request's rows (padding already trimmed) or raises the failure."""

    __slots__ = ("_done", "_result", "_error")

    def __init__(self):
        self._done = threading.Event()
        self._result: Optional[np.ndarray] = None
        self._error: Optional[BaseException] = None

    def _set_result(self, value: np.ndarray) -> None:
        self._result = value
        self._done.set()

    def _set_error(self, exc: BaseException) -> None:
        self._error = exc
        self._done.set()

    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        if not self._done.wait(timeout):
            raise TimeoutError("inference result not ready")
        if self._error is not None:
            raise self._error
        return self._result


class _Request:
    __slots__ = ("x", "n", "future", "deadline_t", "enq_ts", "enq_t0",
                 "trace_ctx")

    def __init__(self, x: np.ndarray, deadline_s: float, trace_ctx):
        self.x = x
        self.n = int(x.shape[0])
        self.future = InferFuture()
        self.enq_ts = wall_ts()
        self.enq_t0 = time.perf_counter()  # lint-obs: ok (request enqueue/deadline clock, not a measured region)
        self.deadline_t = self.enq_t0 + float(deadline_s)
        self.trace_ctx = trace_ctx


class InferenceReplica:
    """One serving replica: admission queue -> continuous batcher over
    a compiled-per-bucket forward, with atomically swappable weights.

    ``buckets`` are the padded batch sizes the forward compiles for
    (ascending; the largest bounds one batch's rows). ``max_queue_rows``
    bounds admission — beyond it, :meth:`submit` raises
    :class:`Overloaded` (the counted 429). ``heartbeat_dir`` publishes
    per-replica liveness the router's ft-policy health checks consume.
    The compiled forward, device placement, preprocess/postprocess
    fusion, and mesh handling are
    :class:`~sparktorch_tpu.inference.BatchPredictor`'s — this class
    adds the online admission/coalescing/liveness layer on top.
    """

    def __init__(self, module, params, model_state=None, mesh=None,
                 replica_id="0", buckets: Sequence[int] = DEFAULT_BUCKETS,
                 max_queue_rows: int = 256,
                 default_deadline_s: float = 30.0,
                 preprocess=None, postprocess=None,
                 telemetry=None, heartbeat_dir: Optional[str] = None,
                 heartbeat_interval_s: float = 0.25,
                 warm_input=None, auto_start: bool = True,
                 params_version: int = 0):
        from sparktorch_tpu.inference import BatchPredictor
        from sparktorch_tpu.obs import get_telemetry

        self.replica_id = str(replica_id)
        self.telemetry = telemetry or get_telemetry()
        self._labels = {"replica": self.replica_id}
        self.buckets = tuple(sorted(int(b) for b in buckets))
        if not self.buckets or self.buckets[0] < 1:
            raise ValueError(f"buckets must be positive, got {buckets!r}")
        self.max_queue_rows = int(max_queue_rows)
        self.default_deadline_s = float(default_deadline_s)
        self._bp = BatchPredictor(
            module, params, model_state=model_state, mesh=mesh,
            chunk=self.buckets[-1], preprocess=preprocess,
            postprocess=postprocess, telemetry=self.telemetry,
        )
        # The coherent serving pair, swapped BETWEEN batches: the loop
        # reads (params, model_state) in one atomic slot read per
        # batch, so a live weight update can never mix new params with
        # old state inside one compiled call.
        self._slot = VersionedSlot((self._bp._params,
                                    self._bp._model_state))
        self.params_version = int(params_version)
        self._cond = threading.Condition()
        self._queue: "collections.deque[_Request]" = collections.deque()
        self._queued_rows = 0
        self._admitted = 0
        self._batches = 0
        self._dead = False
        self._stopped = False
        self._hb = None
        if heartbeat_dir:
            from sparktorch_tpu.obs import HeartbeatEmitter

            self._hb = HeartbeatEmitter(heartbeat_dir,
                                        rank=int(self.replica_id),
                                        telemetry=self.telemetry)
        self._hb_interval = float(heartbeat_interval_s)
        self._hb_last = 0.0
        self._thread: Optional[threading.Thread] = None
        self._warmed: set = set()
        self._warm_lock = threading.Lock()
        if warm_input is not None:
            # Compile-once warmup: every bucket shape compiles NOW
            # (one ``(n, *row_shape)`` sample is enough), so the first
            # real request never pays a multi-second XLA compile.
            self._warm_for(tuple(np.asarray(warm_input).shape[1:]),
                           np.asarray(warm_input).dtype)
        if auto_start:
            self.start()

    # -- lifecycle ----------------------------------------------------------

    def _warm_for(self, row_shape: Tuple[int, ...], dtype) -> None:
        """Bucket warmup keyed on the observed row shape (the
        constructor cannot know it unless given ``warm_input`` —
        modules reshape): the first admission of a new shape compiles
        every bucket up front — one stall, then steady state."""
        # A SET of warmed keys, not just the last one: traffic
        # alternating between two request shapes must not re-run the
        # full bucket compile loop in the admission path per request.
        key = (row_shape, str(dtype))
        if key in self._warmed:
            return
        with self._warm_lock:
            if key in self._warmed:
                return
            params, state = self._slot.read()[1]
            t0 = time.perf_counter()  # lint-obs: ok (request-latency histogram clock pair, not a ledger region)
            for b in self.buckets:
                probe = np.zeros((b, *row_shape), dtype)
                np.asarray(self._bp._fwd(params, state,
                                         self._bp._put(probe)))
            self._warmed.add(key)
            self.telemetry.observe("serve.warmup_s",
                                   time.perf_counter() - t0,  # lint-obs: ok (request-latency histogram clock pair, not a ledger region)
                                   labels=self._labels)

    def start(self) -> "InferenceReplica":
        if self._thread is None or not self._thread.is_alive():
            self._dead = False
            self._stopped = False
            self._thread = threading.Thread(
                target=self._serve_loop, daemon=True,
                name=f"infer-replica-{self.replica_id}",
            )
            self._thread.start()
        return self

    def alive(self) -> bool:
        return (not self._dead and not self._stopped
                and self._thread is not None and self._thread.is_alive())

    def kill(self) -> None:
        """Crash the replica (the chaos path): queued requests fail
        with :class:`ReplicaStopped` (the router re-routes them — zero
        drops is the ROUTER'S contract, not a dead replica's), the
        loop thread exits, and heartbeats simply STOP — the last beat
        ages out, which is exactly the silent-death signature the
        ft barrier deadline detects."""
        with self._cond:
            self._dead = True
            pending = list(self._queue)
            self._queue.clear()
            self._queued_rows = 0
            self._cond.notify_all()
        for req in pending:
            req.future._set_error(ReplicaStopped(
                f"replica {self.replica_id} died"))
        self.telemetry.counter("serve.replica_deaths_total",
                               labels=self._labels)

    def stop(self) -> None:
        """Graceful shutdown: queued requests fail fast, the loop
        exits, and the heartbeat closes with ``alive=False`` (a clean
        stop is distinguishable from a crash)."""
        with self._cond:
            self._stopped = True
            pending = list(self._queue)
            self._queue.clear()
            self._queued_rows = 0
            self._cond.notify_all()
        for req in pending:
            req.future._set_error(ReplicaStopped(
                f"replica {self.replica_id} stopped"))
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        if self._hb is not None:
            self._hb.close()

    # -- weights ------------------------------------------------------------

    def install_params(self, params, model_state=None,
                       version: Optional[int] = None) -> None:
        """Atomically swap the serving weights between batches. The
        predictor's own fields update too (so a direct
        ``predictor.predict`` agrees), but the batch loop executes
        from the slot's coherent (params, state) pair."""
        self._bp.update_params(params, model_state=model_state)
        self._slot.swap((self._bp._params, self._bp._model_state))
        if version is not None:
            self.params_version = int(version)
        else:
            self.params_version += 1
        self.telemetry.counter("serve.weight_swaps_total",
                               labels=self._labels)
        self.telemetry.gauge("serve.params_version", self.params_version,
                             labels=self._labels)
        self.telemetry.gauge("serve.weight_last_update_ts", wall_ts(),
                             labels=self._labels)

    @property
    def predictor(self):
        return self._bp

    # -- admission ----------------------------------------------------------

    @property
    def queued_rows(self) -> int:
        return self._queued_rows

    def submit(self, x, deadline_s: Optional[float] = None,
               trace_ctx=None) -> InferFuture:
        """Admit one request (``x``: ``(n, *row_shape)``, n >= 1) into
        the next in-flight batch. Returns immediately with a future;
        raises :class:`Overloaded` (the counted 429) when the queue is
        full, :class:`ReplicaStopped` when the replica is down, and
        ``ValueError`` for a request bigger than the largest bucket
        (that is a batch job — use the :class:`BatchPredictor`)."""
        x = np.asarray(x)
        if x.ndim < 1 or x.shape[0] < 1:
            raise ValueError(f"request needs a leading batch dim, "
                             f"got shape {x.shape}")
        if x.shape[0] > self.buckets[-1]:
            raise ValueError(
                f"request of {x.shape[0]} rows exceeds the largest "
                f"bucket ({self.buckets[-1]}) — batch jobs go through "
                f"BatchPredictor"
            )
        act = _chaos.fire("serve.replica", replica=self.replica_id)
        if act and act.get("delay"):
            # Straggler replica: correct, just slow. Slept in the
            # ADMISSION path so a traced request attributes it to the
            # router's replica hop — network-shaped latency lands on
            # the hop, batch work on `execute`.
            time.sleep(float(act["delay"]))
        if act and act.get("die"):
            self.kill()
        if self._dead or self._stopped:
            raise ReplicaStopped(f"replica {self.replica_id} is down")
        self._warm_for(tuple(x.shape[1:]), x.dtype)
        req = _Request(x, deadline_s if deadline_s is not None
                       else self.default_deadline_s, trace_ctx)
        with self._cond:
            # Re-checked UNDER the condition: kill()/stop() drain the
            # queue under this lock, so a request admitted after the
            # lock-free check above but appended after the drain would
            # otherwise be orphaned — its future never resolves.
            if self._dead or self._stopped:
                raise ReplicaStopped(
                    f"replica {self.replica_id} is down")
            if self._queued_rows + req.n > self.max_queue_rows:
                self.telemetry.counter(
                    "serve.rejected_total",
                    labels={**self._labels, "reason": "backpressure"})
                raise Overloaded(
                    f"replica {self.replica_id} queue full "
                    f"({self._queued_rows}/{self.max_queue_rows} rows)"
                )
            self._queue.append(req)
            self._queued_rows += req.n
            self._admitted += 1
            self._cond.notify()
        self.telemetry.counter("serve.requests_total", labels=self._labels)
        self.telemetry.counter("serve.rows_total", float(req.n),
                               labels=self._labels)
        return req.future

    def infer(self, x, deadline_s: Optional[float] = None,
              timeout: Optional[float] = None) -> np.ndarray:
        """Blocking convenience: submit + wait."""
        return self.submit(x, deadline_s=deadline_s).result(
            timeout if timeout is not None
            else (deadline_s or self.default_deadline_s) + 5.0)

    # -- the batch loop -----------------------------------------------------

    def _beat(self, force: bool = False) -> None:
        if self._hb is None:
            return
        now = time.monotonic()
        if force or now - self._hb_last >= self._hb_interval:
            self._hb_last = now
            self._hb.notify_step(self._batches)

    def _pop_batch(self) -> List[_Request]:
        """Coalesce queued requests (FIFO, deterministic) into one
        batch up to the largest bucket. Only requests sharing the
        head's (row_shape, dtype) coalesce — np.concatenate across
        mixed shapes would crash the shared batch; a mismatched head
        simply starts the NEXT batch, FIFO order preserved. Called
        under the condition."""
        batch: List[_Request] = []
        rows = 0
        key = None
        while self._queue and rows + self._queue[0].n <= self.buckets[-1]:
            head = self._queue[0]
            hkey = (head.x.shape[1:], head.x.dtype)
            if key is None:
                key = hkey
            elif hkey != key:
                break
            req = self._queue.popleft()
            self._queued_rows -= req.n
            rows += req.n
            batch.append(req)
        return batch

    def _serve_loop(self) -> None:
        from sparktorch_tpu.obs.rpctrace import tracer_for

        tracer = tracer_for(self.telemetry)
        tele = self.telemetry
        while True:
            with self._cond:
                while (not self._queue and not self._dead
                       and not self._stopped):
                    self._cond.wait(timeout=self._hb_interval)
                    self._beat()  # idle liveness: beats without traffic
                if self._dead or self._stopped:
                    return
                batch = self._pop_batch()
                depth = self._queued_rows
            tele.observe("serve.queue_depth", depth, labels=self._labels)
            pop_t0 = time.perf_counter()  # lint-obs: ok (request-latency histogram clock pair, not a ledger region)

            live: List[_Request] = []
            for req in batch:
                if pop_t0 > req.deadline_t:
                    # Expired while queued: fail it here rather than
                    # burn a batch slot computing rows nobody waits
                    # for.
                    tele.counter("serve.deadline_expired_total",
                                 labels=self._labels)
                    req.future._set_error(DeadlineExceeded(
                        f"deadline lapsed after "
                        f"{pop_t0 - req.enq_t0:.3f}s in queue"))
                else:
                    live.append(req)
            if not live:
                continue

            rows = sum(r.n for r in live)
            bucket = next(b for b in self.buckets if b >= rows)

            # ONE slot read per batch: params and model_state flip
            # together (the live-update atomicity contract).
            _sv, (params, state) = self._slot.read()
            exec_ts = wall_ts()
            exec_t0 = time.perf_counter()  # lint-obs: ok (request-latency histogram clock pair, not a ledger region)
            try:
                # Pad/concat inside the guarded region: ANY failure
                # assembling or executing the batch must fail this
                # batch's futures, never kill the loop thread (a dead
                # loop orphans every queued request silently).
                xs = [r.x for r in live]
                if rows < bucket:
                    xs.append(np.zeros((bucket - rows, *xs[0].shape[1:]),
                                       xs[0].dtype))
                padded = xs[0] if len(xs) == 1 else np.concatenate(xs)
                out = np.asarray(
                    self._bp._fwd(params, state, self._bp._put(padded)))
            except Exception as e:  # noqa: BLE001 - batch must not kill loop
                tele.counter("serve.batch_errors_total",
                             labels=self._labels)
                for req in live:
                    req.future._set_error(e)
                continue
            exec_dur = time.perf_counter() - exec_t0  # lint-obs: ok (request-latency histogram clock pair, not a ledger region)
            done_t = time.perf_counter()  # lint-obs: ok (request-latency histogram clock pair, not a ledger region)
            self._batches += 1
            self._beat(force=True)

            tele.observe("serve.batch_fill", rows / bucket,
                         labels=self._labels)
            tele.observe("serve.batch_exec_s", exec_dur,
                         labels=self._labels)
            tele.counter("serve.batches_total", labels=self._labels)
            tele.gauge("serve.last_bucket", bucket, labels=self._labels)

            offset = 0
            for req in live:
                req_out = out[offset:offset + req.n]
                offset += req.n
                if req.trace_ctx is not None and req.trace_ctx.sampled:
                    # The router's replica-hop span is the parent:
                    # queue_wait (admission -> batch pop) and execute
                    # (the shared compiled call) land under it, so the
                    # waterfall says WHERE the request's time went.
                    tracer.record("queue_wait", req.trace_ctx,
                                  req.enq_ts, pop_t0 - req.enq_t0,
                                  kind="server",
                                  replica=self.replica_id)
                    tracer.record("execute", req.trace_ctx, exec_ts,
                                  exec_dur, kind="server",
                                  replica=self.replica_id,
                                  bucket=bucket, batch_rows=rows)
                tele.observe("serve.request_latency_s",
                             done_t - req.enq_t0, labels=self._labels)
                req.future._set_result(req_out)


# ---------------------------------------------------------------------------
# Live weight updates
# ---------------------------------------------------------------------------


class WeightPuller:
    """Background weight refresh for one replica.

    ``transport`` is anything speaking the hogwild pull contract:

    - a :class:`~sparktorch_tpu.net.transport.BinaryTransport` —
      version-tagged pulls against a single param server; when the
      server also serves ``/delta.bin`` (the fleet GATEWAY's
      assembled deltas, or a shard that owns the WHOLE tree —
      single-shard fleet), per-tensor DELTA pulls are used
      automatically (only advanced leaves ship; 404 from a pre-delta
      server degrades to full pulls, once, permanently). A bare shard
      of a multi-shard fleet serves only its hash range — point the
      transport at the gateway, or use a ShardedTransport, for those;
    - a :class:`~sparktorch_tpu.net.sharded.ShardedTransport` — delta
      scatter/gather across the shard fleet (its ``pull`` is already
      delta-based internally).

    Every fresh pull installs atomically via
    :meth:`InferenceReplica.install_params`; a pull failure counts and
    leaves the replica serving its last-good weights (staleness is
    the correct degraded mode for serving — never an outage).
    """

    def __init__(self, replica: InferenceReplica, transport,
                 poll_s: float = 0.05, quant: Optional[str] = None,
                 telemetry=None):
        self.replica = replica
        self.transport = transport
        self.poll_s = float(poll_s)
        self.quant = quant
        self.telemetry = telemetry or replica.telemetry
        self._labels = dict(replica._labels)
        self._have = -1
        self._epoch: Optional[int] = None
        self._leaves: Dict[Tuple[str, ...], np.ndarray] = {}
        # None = undecided (probe /delta.bin first); False = the
        # server 404'd it (pre-delta wire) — full pulls from then on.
        self._use_delta: Optional[bool] = (
            None if hasattr(transport, "pull_delta") else False
        )
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "WeightPuller":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name=f"weight-puller-{self.replica.replica_id}",
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        close = getattr(self.transport, "close", None)
        if close is not None:
            close()

    @property
    def version(self) -> int:
        return self._have

    def poll_once(self) -> bool:
        """One pull sweep; True when fresh weights were installed."""
        t0 = time.perf_counter()  # lint-obs: ok (request-latency histogram clock pair, not a ledger region)
        try:
            if self._use_delta is not False:
                fresh = self._poll_delta()
            else:
                fresh = self._poll_full()
        finally:
            self.telemetry.observe("serve.weight_poll_s",
                                   time.perf_counter() - t0,  # lint-obs: ok (request-latency histogram clock pair, not a ledger region)
                                   labels=self._labels)
        if fresh:
            self.telemetry.counter("serve.weight_updates_total",
                                   labels=self._labels)
        return fresh

    def _poll_delta(self) -> bool:
        try:
            res = self.transport.pull_delta(lambda: self._have,
                                            quant=self.quant)
        except TransportError as e:
            if self._use_delta is None and "404" in str(e):
                # Pre-delta server (single ParameterServer): remember
                # and fall back to full version-tagged pulls.
                self._use_delta = False
                return self._poll_full()
            raise
        self._use_delta = True
        epoch = res.get("epoch")
        if (epoch is not None and self._epoch is not None
                and epoch != self._epoch):
            # Server slot rebuilt (restart/re-add): its version
            # counter restarted, our have-version and leaf cache are
            # meaningless — full resync.
            self._have = -1
            self._leaves.clear()
            self.telemetry.counter("serve.weight_epoch_resyncs_total",
                                   labels=self._labels)
            res = self.transport.pull_delta(lambda: self._have,
                                            quant=self.quant)
            epoch = res.get("epoch")
        if epoch is not None:
            self._epoch = epoch
        if not res.get("fresh"):
            return False
        self._leaves.update(res["leaves"])
        self._have = int(res["version"])
        tree = _wire.unflatten_tree(list(self._leaves.items()))
        self.replica.install_params(tree, version=self._have)
        return True

    def _poll_full(self) -> bool:
        snap = self.transport.pull(self._have)
        if snap is None:
            return False
        version, tree = snap
        self._have = int(version)
        self.replica.install_params(tree, version=self._have)
        return True

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.poll_once()
            except (TransportError, _wire.WireError, OSError):
                # Stale-but-serving beats dead: count it, keep the
                # last-good weights, retry next tick.
                self.telemetry.counter("serve.weight_pull_errors_total",
                                       labels=self._labels)
            self._stop.wait(self.poll_s)


# ---------------------------------------------------------------------------
# Process entry point
# ---------------------------------------------------------------------------


def run_replica_server(torch_obj, replica_id="0",
                       server_url: Optional[str] = None,
                       seed: int = 0,
                       buckets: Sequence[int] = DEFAULT_BUCKETS,
                       max_queue_rows: int = 256,
                       pull_poll_s: float = 0.05,
                       pull_quant: Optional[str] = None,
                       heartbeat_interval_s: float = 1.0,
                       ctx=None) -> Dict[str, int]:
    """ONE inference replica as a standalone process — the serving
    twin of :func:`sparktorch_tpu.serve.fleet.run_shard_server`,
    runnable under ``python -m sparktorch_tpu.ctl.worker`` with
    ``kind='replica_server'``.

    The replica initializes deterministically from ``(torch_obj,
    seed)`` and — when ``server_url`` names a training param server,
    fleet gateway, or anything serving the pull wire — runs a
    :class:`WeightPuller` so a live training run refreshes this
    process's weights continuously (the ft-supervised, elastically
    resized serving fleet). Liveness rides the ctl context's
    heartbeat (step = batches executed), so the controller's stall
    and death policies apply unchanged. Blocks until the context's
    cancel event (SIGTERM under the ctl entry).

    Request ingress is the in-process ``submit`` surface; the remote
    ``/infer`` HTTP frontend is the ROADMAP's filed follow-up — this
    entry is the process-isolation + supervision + live-weights half
    of "replicas as real processes/hosts".
    """
    import jax

    from sparktorch_tpu.utils.serde import deserialize_model

    spec = deserialize_model(torch_obj)
    variables = dict(spec.init_params(jax.random.key(seed)))
    params = variables.pop("params", variables)
    telemetry = getattr(ctx, "telemetry", None)
    # Stack sampler beside the replica's ledger (the ctl entry
    # installs both; a bare in-process replica gets them here).
    from sparktorch_tpu.obs import profile as _profile

    _profile.ensure(telemetry)
    replica = InferenceReplica(
        spec.make_module(), params, model_state=variables or None,
        replica_id=replica_id, buckets=buckets,
        max_queue_rows=max_queue_rows, telemetry=telemetry,
    )
    puller = None
    if server_url:
        from sparktorch_tpu.net.transport import BinaryTransport

        puller = WeightPuller(
            replica, BinaryTransport(server_url, quant=pull_quant),
            poll_s=pull_poll_s, telemetry=telemetry,
        ).start()
    cancel = getattr(ctx, "cancel", None) or threading.Event()
    hb = getattr(ctx, "heartbeat", None)
    try:
        while not cancel.wait(heartbeat_interval_s):
            if hb is not None:
                hb.notify_step(replica._batches)
    finally:
        if puller is not None:
            puller.stop()
        replica.stop()
    return {"replica_id": str(replica_id),
            "batches": int(replica._batches),
            "params_version": int(replica.params_version)}
