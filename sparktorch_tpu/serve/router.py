"""Load-aware multi-replica router for the online inference tier.

One replica is a latency domain; production traffic needs N of them
behind a router that (a) ROUTES on load — weighted least-outstanding,
where the weight is each replica's scraped latency, so a straggling
replica organically sheds traffic — (b) EVICTS replicas the ft
signals call dead (a failed hop, or a heartbeat aged past the
:class:`~sparktorch_tpu.ft.policy.BarrierPolicy` deadline — the same
alive-but-wedged detector the training supervisor uses) and RE-ADMITS
them on recovery, and (c) never drops a request a live replica could
serve: a hop that fails mid-request is retried on the remaining
replicas until the request's own deadline, which is what makes a
chaos-injected replica kill cost latency, not answers.

Latency weights come from the :class:`~sparktorch_tpu.obs.collector.
FleetCollector`'s scraped ``serve.request_latency_s`` histograms when
a collector is attached (the production shape: replicas export, the
collector merges, the router reads one snapshot) and fall back to the
replica buses directly for in-process tiers.

:class:`InferenceTier` bundles the common deployment: N replicas +
router + a restart monitor (a dead replica is rebuilt from its last
served weights, counted, and re-admitted by the router's probe) +
per-replica :class:`~sparktorch_tpu.serve.infer.WeightPuller` threads
against a parameter server/fleet, so a training run's pushes reach
every serving replica within one poll interval.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from sparktorch_tpu.ft.policy import FtPolicy
from sparktorch_tpu.net.transport import TransportError
from sparktorch_tpu.obs import goodput as _goodput
from sparktorch_tpu.serve.infer import (
    DeadlineExceeded,
    InferenceReplica,
    Overloaded,
    ReplicaStopped,
    WeightPuller,
)

_LATENCY_FLOOR_S = 1e-3  # score floor: an unmeasured replica is "fast"


class NoReplicasAvailable(RuntimeError):
    """Every replica is evicted or refused — the router's 503."""

    status = 503


class _ReplicaState:
    __slots__ = ("handle", "outstanding", "evicted", "evict_reason",
                 "evicted_at", "probe_attempts")

    def __init__(self, handle):
        self.handle = handle
        self.outstanding = 0
        self.evicted = False
        self.evict_reason: Optional[str] = None
        self.evicted_at: Optional[float] = None
        self.probe_attempts = 0


class Router:
    """Route requests across registered replicas.

    ``ft_policy`` supplies the health semantics this module REUSES
    rather than reinvents: ``barrier.deadline_s`` bounds a replica's
    heartbeat age (evict an alive-but-wedged replica), ``restart``
    spaces re-admission probes with the same seeded backoff the
    training supervisor uses. ``heartbeat_dir`` is the replicas'
    shared heartbeat directory (rank == replica id);
    without one, liveness falls back to the handles' ``alive()``.
    ``collector`` (a started :class:`FleetCollector`) makes routing
    weights come from scraped metrics instead of in-process buses.
    """

    def __init__(self, ft_policy: Optional[FtPolicy] = None,
                 heartbeat_dir: Optional[str] = None,
                 collector=None, telemetry=None,
                 probe_interval_s: float = 0.25,
                 default_deadline_s: float = 30.0):
        from sparktorch_tpu.obs import get_telemetry

        self.policy = ft_policy or FtPolicy()
        self.heartbeat_dir = heartbeat_dir
        self.collector = collector
        self.telemetry = telemetry or get_telemetry()
        self.probe_interval_s = float(probe_interval_s)
        self.default_deadline_s = float(default_deadline_s)
        self._rng = random.Random(self.policy.seed)
        self._lock = threading.Lock()
        self._replicas: Dict[str, _ReplicaState] = {}
        # Routing-weight cache: a fresh p50 read costs a percentile
        # over the histogram ring (or a collector snapshot merge).
        # The bus now snapshots the ring under its lock and computes
        # the percentile OUTSIDE it (obs.telemetry.rollup_from_state —
        # the PR 9 regression where per-request reads serialized the
        # router against its own replicas, 3x throughput at 400
        # threads, is pinned by test_obs_history's contention test),
        # but the math itself is still worth amortizing: load shifts
        # on the outstanding term instantly; the latency WEIGHT only
        # needs to follow on this horizon.
        self._p50_ttl_s = 0.25
        self._p50_cache: Dict[str, Tuple[float, Optional[float]]] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- membership ---------------------------------------------------------

    def register(self, replica) -> None:
        """Add (or REPLACE — the restart-monitor path) a replica
        handle. A replacement for an evicted id stays evicted until a
        health probe passes, so re-admission is always observed and
        counted, never assumed."""
        rid = str(replica.replica_id)
        with self._lock:
            prior = self._replicas.get(rid)
            st = _ReplicaState(replica)
            if prior is not None and prior.evicted:
                st.evicted = True
                st.evict_reason = prior.evict_reason
                st.evicted_at = prior.evicted_at
                st.probe_attempts = prior.probe_attempts
            self._replicas[rid] = st
        self._gauge_live()

    def replicas(self) -> Dict[str, Any]:
        with self._lock:
            return {rid: st.handle for rid, st in self._replicas.items()}

    def _gauge_live(self) -> None:
        with self._lock:
            live = sum(not st.evicted for st in self._replicas.values())
        self.telemetry.gauge("router.live_replicas", live)

    # -- health -------------------------------------------------------------

    def _hb_ranks(self) -> Optional[Dict[int, Any]]:
        """One heartbeat-directory scan, shared by a whole health
        sweep — per-replica rescans multiply a full dir parse by N
        replicas per tick (and by every submit thread during an
        eviction window)."""
        if not self.heartbeat_dir:
            return None
        from sparktorch_tpu.obs import gang_report

        return gang_report(self.heartbeat_dir).get("ranks", {})

    @staticmethod
    def _hb_age(rid: str, ranks: Optional[Dict[int, Any]]
                ) -> Optional[float]:
        if ranks is None:
            return None
        try:
            rank = int(rid)
        except ValueError:
            return None
        rec = ranks.get(rank)
        if rec is None:
            return None
        return float(rec.get("last_seen_age_s", 0.0))

    def evict(self, rid: str, reason: str = "error") -> None:
        with self._lock:
            st = self._replicas.get(rid)
            if st is None or st.evicted:
                return
            st.evicted = True
            st.evict_reason = reason
            st.evicted_at = time.monotonic()
            st.probe_attempts = 0
        self.telemetry.counter("router.evictions_total",
                               labels={"replica": rid, "reason": reason})
        self._gauge_live()

    def _probe(self, rid: str, st: _ReplicaState,
               hb_ranks: Optional[Dict[int, Any]]) -> bool:
        """One health decision for ``rid``: handle liveness AND (when
        a heartbeat dir is wired) heartbeat freshness under the
        barrier deadline — the exporter-vanished/wedged case handle
        liveness alone cannot see."""
        try:
            ok = bool(st.handle.alive())
        except Exception:  # noqa: BLE001 - a probe must never raise
            ok = False
        if ok:
            age = self._hb_age(rid, hb_ranks)
            if age is not None and age > self.policy.barrier.deadline_s:
                ok = False
        return ok

    def check_health(self) -> None:
        """One sweep: evict live replicas that fail the probe, re-admit
        evicted ones that pass it (probe spacing for evicted replicas
        follows the restart policy's seeded backoff — the supervisor's
        discipline, reused). Runs from the background loop and inline
        from :meth:`submit` when no live replica remains."""
        with self._lock:
            snapshot = list(self._replicas.items())
        now = time.monotonic()
        hb_ranks = self._hb_ranks()
        for rid, st in snapshot:
            if st.evicted:
                delay = self.policy.restart.delay_s(st.probe_attempts,
                                                    self._rng)
                if st.evicted_at is not None \
                        and now - st.evicted_at < delay:
                    continue
                if self._probe(rid, st, hb_ranks):
                    with self._lock:
                        cur = self._replicas.get(rid)
                        if cur is not None and cur.evicted:
                            cur.evicted = False
                            cur.evict_reason = None
                    self.telemetry.counter("router.readmissions_total",
                                           labels={"replica": rid})
                    self._gauge_live()
                else:
                    st.probe_attempts += 1
                    st.evicted_at = now
            else:
                if not self._probe(rid, st, hb_ranks):
                    self.evict(rid, reason="health")

    def start(self) -> "Router":
        """Launch the background health loop (optional — an in-process
        tier that only ever fails on submit can rely on the inline
        sweeps)."""
        # Stack sampler beside the router's goodput attribution
        # (site=router spans in submit): serving processes profile
        # like training ones.
        from sparktorch_tpu.obs import profile as _profile

        _profile.ensure(self.telemetry)
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(target=self._health_loop,
                                            daemon=True,
                                            name="router-health")
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def _health_loop(self) -> None:
        while not self._stop.wait(self.probe_interval_s):
            self.check_health()

    # -- routing ------------------------------------------------------------

    def _latency_p50(self, rid: str, st: _ReplicaState) -> Optional[float]:
        now = time.monotonic()
        cached = self._p50_cache.get(rid)
        if cached is not None and now - cached[0] < self._p50_ttl_s:
            return cached[1]
        p50 = self._latency_p50_fresh(rid, st)
        self._p50_cache[rid] = (now, p50)
        return p50

    def _latency_p50_fresh(self, rid: str,
                           st: _ReplicaState) -> Optional[float]:
        labels = {"replica": rid}
        if self.collector is not None:
            from sparktorch_tpu.obs import snapshot_histogram

            roll = snapshot_histogram(self.collector.merged_snapshot(),
                                      "serve.request_latency_s", labels)
            if roll and roll.get("p50") is not None:
                return float(roll["p50"])
            return None
        tele = getattr(st.handle, "telemetry", None)
        if tele is None:
            return None
        roll = tele.histogram("serve.request_latency_s", labels)
        return float(roll["p50"]) if roll.get("p50") is not None else None

    def _choose(self, exclude: set) -> Optional[str]:
        """Weighted least-outstanding: score = (outstanding + 1) x
        p50 latency (the classic weighted-least-connection estimate of
        this replica's expected wait). Unmeasured replicas take the
        latency floor — new capacity attracts traffic until its real
        latency shows up. Deterministic tie-break by id."""
        with self._lock:
            candidates = [(rid, st) for rid, st in self._replicas.items()
                          if not st.evicted and rid not in exclude]
        best_rid, best_score = None, None
        for rid, st in sorted(candidates):
            p50 = self._latency_p50(rid, st)
            score = (st.outstanding + 1) * max(
                p50 if p50 is not None else 0.0, _LATENCY_FLOOR_S)
            if best_score is None or score < best_score:
                best_rid, best_score = rid, score
        return best_rid

    def submit(self, x, deadline_s: Optional[float] = None) -> np.ndarray:
        """Route one request; blocks until a replica answers. A hop
        failure (replica died, timed out, or was killed mid-batch)
        evicts that replica and re-routes the SAME request to the
        remaining ones — requests are pure reads, so the retry is
        safe — until the request's deadline. Raises
        :class:`Overloaded` when every live replica refused admission
        (the tier-wide 429) and :class:`NoReplicasAvailable` when the
        deadline lapses with no live replica."""
        from sparktorch_tpu.obs.rpctrace import tracer_for

        tracer = tracer_for(self.telemetry)
        budget = (deadline_s if deadline_s is not None
                  else self.default_deadline_s)
        deadline = time.monotonic() + budget
        tried: set = set()
        all_overloaded_rounds = 0
        wait_s = min(0.02, self.probe_interval_s)
        self.telemetry.counter("router.requests_total")
        with tracer.root_span("infer", kind="client") as root:
            while True:
                rid = self._choose(tried)
                if rid is None:
                    # Nothing routable right now. If untried replicas
                    # may come back (monitor restart, probe pass), wait
                    # a beat and retry the FULL set inside the
                    # deadline; a request must survive the eviction
                    # window of a replica kill.
                    if time.monotonic() >= deadline:
                        if tried and all_overloaded_rounds > 0:
                            self.telemetry.counter("router.rejects_total")
                            raise Overloaded(
                                "every live replica refused admission")
                        self.telemetry.counter("router.unroutable_total")
                        raise NoReplicasAvailable(
                            f"no live replica within {budget}s")
                    self.check_health()
                    tried.clear()
                    # Refusals reset with the round: a 429 from a
                    # replica that has since DIED must not turn the
                    # deadline's verdict from 503 into 429.
                    all_overloaded_rounds = 0
                    # Doubling backoff (20ms -> 100ms cap): under
                    # SUSTAINED uniform overload each retry round
                    # costs every replica a refused admission — the
                    # backoff cuts that spam ~5x while a short-lived
                    # eviction window still gets a fast first retry.
                    # The request's own deadline stays the shed knob:
                    # a client that wants a fast tier-wide 429 passes
                    # a short deadline.
                    # Retry backoff is ROUTER-attributed wall: the
                    # goodput ledger's serving story stops at replicas
                    # without it (ROADMAP's "route/hop/retry work").
                    with _goodput.span("exposed_comm",
                                       {"site": "router_retry"}):
                        time.sleep(wait_s)
                    wait_s = min(wait_s * 2, 0.1)
                    continue
                wait_s = min(0.02, self.probe_interval_s)
                with self._lock:
                    st = self._replicas[rid]
                    st.outstanding += 1
                remaining = max(deadline - time.monotonic(), 0.001)
                with tracer.child_span("replica", root.ctx,
                                       kind="client",
                                       replica=rid) as tsp, \
                        _goodput.span("exposed_comm",
                                      {"site": "router"}):
                    # The hop (submit + queue + replica wall) is
                    # router-attributed exposed_comm on THIS process's
                    # ledger; the replica's own ledger attributes its
                    # compute — different processes, no double count.
                    try:
                        fut = st.handle.submit(
                            x, deadline_s=remaining,
                            trace_ctx=tsp.ctx,
                        )
                        out = fut.result(timeout=remaining + 1.0)
                        self.telemetry.counter(
                            "router.routed_total",
                            labels={"replica": rid})
                        return out
                    except Overloaded as e:
                        # Healthy but full: not an eviction — try the
                        # others, shed only when everyone says 429.
                        tsp.set_error(e)
                        tried.add(rid)
                        all_overloaded_rounds += 1
                    except DeadlineExceeded as e:
                        # The REQUEST's own budget lapsed while queued
                        # — load, not replica death. Nothing left to
                        # retry with; surface it as-is.
                        tsp.set_error(e)
                        self.telemetry.counter(
                            "router.deadline_exceeded_total")
                        raise
                    except (ReplicaStopped, TransportError, OSError,
                            TimeoutError) as e:
                        tsp.set_error(e)
                        self.evict(rid, reason="error")
                        tried.add(rid)
                    finally:
                        with self._lock:
                            cur = self._replicas.get(rid)
                            if cur is not None:
                                cur.outstanding = max(
                                    0, cur.outstanding - 1)

    @property
    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                rid: {"outstanding": st.outstanding,
                      "evicted": st.evicted,
                      "evict_reason": st.evict_reason}
                for rid, st in self._replicas.items()
            }


# ---------------------------------------------------------------------------
# The bundled tier: replicas + router + restart monitor + pullers
# ---------------------------------------------------------------------------


class InferenceTier:
    """N continuous-batching replicas behind one router, with the
    recovery loop wired: a dead replica (chaos kill, batch-loop crash)
    is rebuilt from its last served weights after the restart policy's
    backoff, re-registered, and re-admitted by the router's health
    probe — the serving twin of the param-server fleet's shard
    monitor. ``start_pullers(transport_factory)`` attaches one
    :class:`WeightPuller` per replica (the factory is called once per
    replica AND per restart — transports are connection-owning and
    must not be shared across threads)."""

    def __init__(self, module, params, model_state=None,
                 n_replicas: int = 2, mesh=None,
                 buckets=None, max_queue_rows: int = 256,
                 default_deadline_s: float = 30.0,
                 telemetry=None, heartbeat_dir: Optional[str] = None,
                 ft_policy: Optional[FtPolicy] = None, collector=None,
                 warm_input=None, restart_replicas: bool = True,
                 probe_interval_s: float = 0.1):
        from sparktorch_tpu.obs import get_telemetry

        if n_replicas < 1:
            raise ValueError(f"n_replicas must be >= 1, got {n_replicas}")
        self.telemetry = telemetry or get_telemetry()
        self._module = module
        self._mesh = mesh
        self._buckets = buckets
        self._max_queue_rows = max_queue_rows
        self._default_deadline_s = default_deadline_s
        self._heartbeat_dir = heartbeat_dir
        self._warm_input = warm_input
        self.policy = ft_policy or FtPolicy()
        self.router = Router(ft_policy=self.policy,
                             heartbeat_dir=heartbeat_dir,
                             collector=collector,
                             telemetry=self.telemetry,
                             probe_interval_s=probe_interval_s,
                             default_deadline_s=default_deadline_s)
        self.replicas: Dict[str, InferenceReplica] = {}
        for i in range(n_replicas):
            self.replicas[str(i)] = self._build_replica(
                str(i), params, model_state)
        for replica in self.replicas.values():
            self.router.register(replica)
        self.router.start()
        self._pullers: Dict[str, WeightPuller] = {}
        self._puller_factory: Optional[Callable[[], Any]] = None
        self._puller_kwargs: Dict[str, Any] = {}
        self._rng = self.policy.rng()
        self._restart_attempts: Dict[str, int] = {}
        self._restart_at: Dict[str, float] = {}
        self._rebuilding: set = set()
        self._monitor_stop = threading.Event()
        self._monitor: Optional[threading.Thread] = None
        if restart_replicas:
            self._monitor = threading.Thread(target=self._monitor_loop,
                                             daemon=True,
                                             name="tier-monitor")
            self._monitor.start()

    def _build_replica(self, rid: str, params,
                       model_state=None,
                       params_version: int = 0) -> InferenceReplica:
        kwargs = {}
        if self._buckets is not None:
            kwargs["buckets"] = self._buckets
        return InferenceReplica(
            self._module, params, model_state=model_state,
            mesh=self._mesh, replica_id=rid,
            max_queue_rows=self._max_queue_rows,
            default_deadline_s=self._default_deadline_s,
            telemetry=self.telemetry,
            heartbeat_dir=self._heartbeat_dir,
            warm_input=self._warm_input,
            params_version=params_version, **kwargs,
        )

    # -- serving ------------------------------------------------------------

    def submit(self, x, deadline_s: Optional[float] = None) -> np.ndarray:
        return self.router.submit(x, deadline_s=deadline_s)

    # -- live weights -------------------------------------------------------

    def start_pullers(self, transport_factory: Callable[[], Any],
                      poll_s: float = 0.05,
                      quant: Optional[str] = None) -> None:
        """One weight puller per replica against ``transport_factory()``
        (a fresh transport per replica — they are worker-owned)."""
        self._puller_factory = transport_factory
        self._puller_kwargs = {"poll_s": poll_s, "quant": quant}
        for rid, replica in self.replicas.items():
            self._attach_puller(rid, replica)

    def _attach_puller(self, rid: str, replica: InferenceReplica) -> None:
        if self._puller_factory is None:
            return
        old = self._pullers.pop(rid, None)
        if old is not None:
            old.stop()
        self._pullers[rid] = WeightPuller(
            replica, self._puller_factory(),
            telemetry=self.telemetry, **self._puller_kwargs,
        ).start()

    # -- recovery -----------------------------------------------------------

    def _monitor_loop(self) -> None:
        while not self._monitor_stop.wait(0.05):
            now = time.monotonic()
            for rid in list(self.replicas):
                replica = self.replicas[rid]
                if rid in self._rebuilding:
                    continue
                if replica.alive():
                    self._restart_attempts.pop(rid, None)
                    self._restart_at.pop(rid, None)
                    continue
                attempt = self._restart_attempts.get(rid, 0)
                if attempt >= self.policy.restart.max_restarts:
                    continue  # budget spent: stays evicted
                at = self._restart_at.get(rid)
                if at is None:
                    # Scheduled restart (the supervisor's discipline:
                    # a timestamp the loop checks, never an inline
                    # sleep — N deaths recover in max-of-backoffs).
                    self._restart_at[rid] = now + \
                        self.policy.restart.delay_s(attempt, self._rng)
                    continue
                if now < at:
                    continue
                self._restart_at.pop(rid, None)
                self._restart_attempts[rid] = attempt + 1
                # Rebuild in a thread PER replica: _build_replica's
                # bucket warmup is seconds of XLA compile, and a
                # serial loop would recover N concurrent deaths in
                # sum-of-compiles — the max-of-backoffs discipline
                # demands the rebuilds overlap too.
                self._rebuilding.add(rid)
                threading.Thread(
                    target=self._rebuild_replica, args=(rid, replica),
                    daemon=True, name=f"tier-rebuild-{rid}",
                ).start()

    def _rebuild_replica(self, rid: str, dead: InferenceReplica) -> None:
        t0 = time.monotonic()
        try:
            # Rebuild from the dead replica's LAST SERVED weights
            # (freshest state it had); the puller then closes any
            # staleness against the param server.
            _v, (params, state) = dead._slot.read()
            fresh = self._build_replica(
                rid, params, model_state=state,
                params_version=dead.params_version)
            # Counted BEFORE the fresh replica is exposed: anything
            # that observes the recovered replica (a waiter polling
            # alive(), the bench's kill gate) must also see the
            # restart counter — the reverse order races.
            self.telemetry.counter("serve.replica_restarts_total",
                                   labels={"replica": rid})
            self.telemetry.observe("serve.replica_recovery_s",
                                   time.monotonic() - t0,
                                   labels={"replica": rid})
            self.replicas[rid] = fresh
            self.router.register(fresh)
            self._attach_puller(rid, fresh)
        except Exception:  # noqa: BLE001 - a failed rebuild retries
            # The attempt is already counted; the monitor reschedules
            # under the same backoff until the budget runs out.
            self.telemetry.counter("serve.replica_restart_failures_total",
                                   labels={"replica": rid})
        finally:
            self._rebuilding.discard(rid)

    def stop(self) -> None:
        self._monitor_stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout=5.0)
            self._monitor = None
        for puller in self._pullers.values():
            puller.stop()
        self._pullers.clear()
        self.router.stop()
        for replica in self.replicas.values():
            replica.stop()
