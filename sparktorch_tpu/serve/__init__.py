from sparktorch_tpu.serve.param_server import ParameterServer, ParamServerHttp

__all__ = ["ParameterServer", "ParamServerHttp", "ParamServerFleet",
           "ParamShardServer", "InferenceReplica", "InferenceTier",
           "Router", "WeightPuller", "Overloaded", "DeadlineExceeded",
           "ReplicaStopped", "NoReplicasAvailable"]

_INFER = ("InferenceReplica", "WeightPuller", "Overloaded",
          "DeadlineExceeded", "ReplicaStopped")
_ROUTER = ("InferenceTier", "Router", "NoReplicasAvailable")


def __getattr__(name):
    # Lazy: the fleet and the inference tier pull in net.sharded /
    # jax; keep the base import light (and cycle-free) for callers
    # that only want one server.
    if name in ("ParamServerFleet", "ParamShardServer"):
        from sparktorch_tpu.serve import fleet

        return getattr(fleet, name)
    if name in _INFER:
        from sparktorch_tpu.serve import infer

        return getattr(infer, name)
    if name in _ROUTER:
        from sparktorch_tpu.serve import router

        return getattr(router, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
