from sparktorch_tpu.serve.param_server import ParameterServer, ParamServerHttp

__all__ = ["ParameterServer", "ParamServerHttp"]
