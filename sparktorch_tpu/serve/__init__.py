from sparktorch_tpu.serve.param_server import ParameterServer, ParamServerHttp

__all__ = ["ParameterServer", "ParamServerHttp", "ParamServerFleet",
           "ParamShardServer"]


def __getattr__(name):
    # Lazy: the fleet pulls in net.sharded + jax; keep the base import
    # light (and cycle-free) for callers that only want one server.
    if name in ("ParamServerFleet", "ParamShardServer"):
        from sparktorch_tpu.serve import fleet

        return getattr(fleet, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
