"""HBM-resident parameter server for asynchronous (hogwild) training.

Reference: ``sparktorch/server.py`` — a Flask app in a forked process
on the driver holding the canonical model in shared CPU memory
(``share_memory()``, server.py:83), with routes ``GET /`` (liveness,
:89-91), ``GET /parameters`` (full dill state_dict, :93-100),
``POST /update`` (install grads, ``optimizer.step()`` under an RWLock
that both read & write paths take as *write*, :125-147), and
``POST /losses`` (windowed-average early stop, :102-123). It tolerates
up to 10 update errors before raising (:139-142).

TPU-native redesign:

- Canonical params live as **device arrays in HBM** behind a
  :class:`VersionedSlot` — reads are lock-free immutable snapshots,
  so pulls never contend with applies (the reference serializes them,
  SURVEY §5 "both take the write lock").
- Applies run on a **single writer thread** draining a FIFO queue
  through one jitted ``optax`` update — the principled version of
  hogwild's "just step whenever grads arrive", keeping the optimizer
  math on-device and race-free by construction.
- Pulls are **version-tagged**: a client that already holds version N
  gets "nothing newer" instead of a full redundant weight transfer —
  eliminating the reference's 2×model-size-per-iteration HTTP
  pathology (``hogwild.py:103,130``; SURVEY §3.2).
- Transport is split from state: in-process calls for workers in the
  same runtime, and a stdlib-HTTP wire (:class:`ParamServerHttp`)
  with the reference's four routes for remote workers (no Flask in
  this image; the wire format is dill like the reference's).
"""

from __future__ import annotations

import json
import queue
import socket as _socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional, Tuple

import dill
import jax
import numpy as np

from sparktorch_tpu.ft import chaos as _chaos
from sparktorch_tpu.net import wire as binwire
from sparktorch_tpu.obs import goodput as _goodput
from sparktorch_tpu.obs import (
    PROMETHEUS_CONTENT_TYPE,
    Telemetry,
    render_prometheus,
    wall_ts,
)
from sparktorch_tpu.obs import rpctrace as _rpctrace
from sparktorch_tpu.utils.early_stopper import EarlyStopping
from sparktorch_tpu.utils.locks import VersionedSlot
from sparktorch_tpu.utils.serde import ModelSpec, deserialize_model

MAX_TOLERATED_ERRORS = 10  # server.py:139-142 parity


class ParameterServer:
    """Driver-hosted canonical-parameter holder + async applier."""

    def __init__(
        self,
        torch_obj,
        window_len: int = 3,
        early_stop_patience: int = -1,
        acquire_lock: bool = True,
        device: Optional[jax.Device] = None,
        seed: int = 0,
        telemetry: Optional[Telemetry] = None,
    ):
        # The server deserializes its own model copy, like
        # server.py:44-51 — but params go straight to device HBM.
        self.spec: ModelSpec = deserialize_model(torch_obj)
        # Server-scoped bus (not the process global): each server's
        # counters are its own, so a test or driver hosting several
        # servers never cross-talks. The HTTP wire serves this very
        # instance from /metrics.
        self.telemetry = telemetry or Telemetry(run_id="param_server")
        self.device = device or jax.devices()[0]
        self.acquire_lock = acquire_lock  # parity knob; applies are
        # always serialized by the single writer thread.

        self._tx = self.spec.make_optimizer()
        rng = jax.random.key(seed)
        variables = dict(self.spec.init_params(rng))
        params = variables.pop("params", variables)
        params = jax.device_put(params, self.device)
        self._model_state = jax.device_put(variables, self.device)
        self._opt_state = jax.device_put(self._tx.init(params), self.device)
        self.slot = VersionedSlot(params)

        # One compiled apply for the life of the server. Grads arrive
        # in whatever dtype the wire used (bf16 from HttpTransport's
        # compressed pushes); cast up to the param dtype before the
        # optimizer update so moments stay full precision.
        def _apply(params, opt_state, grads):
            grads = jax.tree.map(
                lambda g, p: g.astype(p.dtype), grads, params
            )
            updates, new_opt = self._tx.update(grads, opt_state, params)
            import optax

            return optax.apply_updates(params, updates), new_opt

        self._apply_fn = jax.jit(_apply)

        # Windowed early stop (server.py:102-123 parity).
        self.window_len = max(1, window_len)
        self._losses: list = []
        self._stopper = (
            EarlyStopping(patience=early_stop_patience)
            if early_stop_patience and early_stop_patience > 0
            else None
        )
        self._stop_flag = False
        self._loss_lock = threading.Lock()

        self._queue: "queue.Queue" = queue.Queue()
        self._errors = 0
        self._failed: Optional[BaseException] = None
        self._applied = 0
        self._running = True
        self._writer = threading.Thread(target=self._apply_loop, daemon=True)
        self._writer.start()

    # ------------------------------------------------------------------
    # State access
    # ------------------------------------------------------------------

    def get_parameters(self, have_version: int = -1) -> Optional[Tuple[int, Any]]:
        """Immutable snapshot pull; None if the client is up to date.

        Parity: ``GET /parameters`` (server.py:93-100), minus the
        redundant-transfer pathology.
        """
        snap = self.slot.read_if_newer(have_version)
        self.telemetry.counter("param_server.pulls")
        if snap is not None:
            self.telemetry.counter("param_server.pull_fresh")
        return snap

    def model_state(self):
        return self._model_state

    @property
    def applied_updates(self) -> int:
        return self._applied

    # ------------------------------------------------------------------
    # Gradient path
    # ------------------------------------------------------------------

    def push_gradients(self, grads, wait: bool = True,
                       timeout: float = 60.0, trace_ctx=None) -> None:
        """Enqueue a gradient pytree for the writer thread.

        Parity: ``POST /update`` (server.py:125-147) — the reference
        applies ``optimizer.step()`` synchronously inside the request,
        so a worker's next pull always reflects its own push. With
        ``wait=True`` (default) the same guarantee holds here: the
        call returns once THIS gradient is applied. Applies remain
        FIFO-serialized by the single writer thread; workers never
        barrier against each other (hogwild semantics preserved).
        ``wait=False`` gives fully fire-and-forget pushes.

        ``trace_ctx`` (a sampled span context from the wire) rides the
        queue item so the writer thread can attribute THIS request's
        queue-wait and apply as child spans — the split that tells a
        slow push apart from a backed-up writer.
        """
        if self._failed is not None:
            raise RuntimeError("parameter server failed") from self._failed
        done = threading.Event() if wait else None
        self._queue.put((grads, done, trace_ctx,
                         wall_ts(), time.perf_counter()))  # lint-obs: ok (request-latency histogram clock pair, not a ledger region)
        self.telemetry.counter("param_server.pushes")
        self.telemetry.gauge("param_server.queue_depth", self._queue.qsize())
        if done is not None and not done.wait(timeout):
            raise TimeoutError("parameter server apply timed out")

    def _apply_loop(self):
        tracer = _rpctrace.tracer_for(self.telemetry)
        while self._running:
            try:
                grads, done, tctx, enq_ts, enq_t0 = self._queue.get(
                    timeout=0.1)
            except queue.Empty:
                continue
            try:
                t0 = time.perf_counter()  # lint-obs: ok (request-latency histogram clock pair, not a ledger region)
                # Queue-wait attribution: enqueue happened on a handler
                # thread, the pop here — the after-the-fact record is
                # the only honest way to span it.
                tracer.record("queue_wait", tctx, enq_ts, t0 - enq_t0,
                              kind="server")
                # A serving rank's productive seconds are its applies:
                # the same writer stamp the rpc trace spans, attributed
                # into the ambient goodput ledger's compute bucket
                # (no-op when no ledger is installed on this rank).
                with tracer.child_span("apply", tctx, kind="server"), \
                        _goodput.span("compute", {"site": "ps_apply"}):
                    version, params = self.slot.read()
                    grads = jax.device_put(grads, self.device)
                    new_params, new_opt = self._apply_fn(
                        params, self._opt_state, grads
                    )
                    self._opt_state = new_opt
                    self.slot.swap(new_params)
                self._applied += 1
                self.telemetry.counter("param_server.applies")
                self.telemetry.observe("param_server.apply_s",
                                       time.perf_counter() - t0)  # lint-obs: ok (request-latency histogram clock pair, not a ledger region)
                self.telemetry.gauge("param_server.version", version + 1)
            except Exception as e:  # tolerate a bounded error count
                self._errors += 1
                self.telemetry.counter("param_server.apply_errors")
                if self._errors > MAX_TOLERATED_ERRORS:
                    self._failed = e
                    self._running = False
            finally:
                if done is not None:
                    done.set()
                self._queue.task_done()

    def drain(self, timeout: float = 30.0) -> None:
        """Block until all queued gradients are fully applied (not just
        popped — ``unfinished_tasks`` covers the in-flight apply)."""
        import time

        deadline = time.monotonic() + timeout
        while self._queue.unfinished_tasks and time.monotonic() < deadline:
            time.sleep(0.005)

    # ------------------------------------------------------------------
    # Early stopping
    # ------------------------------------------------------------------

    def post_loss(self, loss: float) -> bool:
        """Windowed-average early-stop vote. Returns True => stop.

        Parity: ``POST /losses`` (server.py:102-123): collect one loss
        per worker, average a full window, feed the patience tracker.
        """
        self.telemetry.counter("param_server.losses_posted")
        with self._loss_lock:
            if self._stop_flag:
                return True
            if self._stopper is None:
                return False
            self._losses.append(float(loss))
            if len(self._losses) >= self.window_len:
                avg = float(np.mean(self._losses))
                self._losses.clear()
                if self._stopper.step(avg):
                    self._stop_flag = True
        return self._stop_flag

    @property
    def should_stop(self) -> bool:
        return self._stop_flag

    # ------------------------------------------------------------------
    # Lifecycle
    # ------------------------------------------------------------------

    def stop(self):
        self._running = False
        if self._writer.is_alive():
            self._writer.join(timeout=5.0)

    def final_state(self):
        """(params, model_state) after draining pending applies —
        what ``hogwild.train`` pulls at the end (hogwild.py:179-182)."""
        self.drain()
        _, params = self.slot.read()
        return params, self._model_state


# ---------------------------------------------------------------------------
# HTTP wire (stdlib; the reference used Flask — server.py:79-149)
# ---------------------------------------------------------------------------


def _to_host(tree):
    return jax.tree.map(lambda a: np.asarray(a), tree)


class _KeepAliveHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer that can actually STOP: with HTTP/1.1
    keep-alive, handler threads park in a blocking read on live client
    sockets, and ``shutdown()`` only stops the accept loop — the old
    connections (and their threads) would survive a ``stop()`` and
    keep serving a supposedly-dead server, which masks restarts (a
    client's "reconnect after server restart" would silently talk to
    the zombie). Track live request sockets and shut them down on
    stop — the same live-fd handling the native gang coordinator does
    in ``gang_server_stop``."""

    daemon_threads = True

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._live_requests: set = set()
        self._live_lock = threading.Lock()

    def process_request(self, request, client_address):
        with self._live_lock:
            self._live_requests.add(request)
        super().process_request(request, client_address)

    def shutdown_request(self, request):
        with self._live_lock:
            self._live_requests.discard(request)
        super().shutdown_request(request)

    def close_all_connections(self):
        with self._live_lock:
            live = list(self._live_requests)
        for sock in live:
            try:
                sock.shutdown(_socket.SHUT_RDWR)
            except OSError:
                pass  # already closing


class ParamServerHttp:
    """Expose a :class:`ParameterServer` over HTTP/1.1.

    Routes mirror the reference wire (hogwild.py:31-62):
    ``GET /`` liveness, ``GET /parameters`` (dill, honors the
    ``X-Have-Version`` header with 204 when not newer),
    ``POST /update`` (dill grads), ``POST /losses`` (dill float ->
    dill {'stop': bool}).

    Binary-wire routes (:mod:`sparktorch_tpu.net.wire`) render from
    the SAME version-keyed snapshot as the dill ones, so a mixed gang
    (dill workers next to binary workers) trains against one coherent
    server: ``GET /parameters.bin`` (framed tensors, ``X-Have-Version``
    honored with a real 304), ``POST /update.bin`` (framed gradient
    tree, quantized tensors dequantized at decode), and
    ``POST /losses.json`` (JSON early-stop vote). The server speaks
    HTTP/1.1 so binary clients keep one connection alive for the whole
    run. Every wire route feeds ``wire_bytes_total{route,dir}`` and a
    per-route latency histogram into the telemetry bus.

    Observability routes beyond the reference: ``GET /metrics`` serves
    the server's telemetry as Prometheus exposition text (scrapeable),
    and ``GET /telemetry`` the same snapshot as JSON — both rendered
    from ONE ``Telemetry.snapshot()``, so a scrape can never disagree
    with the JSONL dump of the same server.

    Fleet mode (:mod:`sparktorch_tpu.serve.fleet`): when the backing
    server exposes ``render_delta`` (a :class:`ParamShardServer`),
    ``GET /delta.bin`` serves per-tensor delta frames — only the
    leaves whose version advanced past the client's
    ``X-Have-Version``, optionally int8-quantized with server-side
    error feedback (``X-Pull-Quant: int8``). Every delta reply (304
    included) carries ``X-Slot-Epoch`` (the slot's boot nonce — a
    restarted/rebuilt server is detected by epoch change, never by
    version arithmetic) and, when ``ring_version_fn`` is given,
    ``X-Ring-Version`` so clients learn about shard add/drain without
    polling. ``shard`` labels every wire metric series with the shard
    id, and ``extra_json_routes`` mounts small JSON control routes
    (the fleet's ``/fleet.json`` topology document).
    """

    def __init__(self, server: ParameterServer, host: str = "127.0.0.1",
                 port: int = 3000, shard: Optional[str] = None,
                 extra_json_routes=None, ring_version_fn=None):
        self.server = server
        self.host = host
        self.port = port
        self.shard = str(shard) if shard is not None else None
        self.extra_json_routes = dict(extra_json_routes or {})
        self.ring_version_fn = ring_version_fn
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self):
        ps = self.server
        # Version-keyed cache of the host snapshot and its rendered
        # wire bodies: materializing device params costs a full host
        # download (on a tunnel-attached chip, seconds per pull) — pay
        # it once per VERSION, not once per worker pull; each wire
        # format (dill / binary frame) then renders lazily from the
        # one host tree, so a mixed gang shares a single download.
        # The slot's version tag makes staleness detection free.
        wire_cache: dict = {"version": None, "host": None,
                            "dill": None, "bin": None}
        wire_lock = threading.Lock()
        # Run-ID correlation: frames this server sends carry the
        # 16-bit tag of its bus run_id; a push tagged with a DIFFERENT
        # nonzero tag is a worker from another run (recycled port,
        # stale supervisor) — counted + flagged, but still applied
        # (the tag is a join key for the collector, not an ACL).
        from sparktorch_tpu.obs.collector import run_tag as _run_tag

        server_tag = _run_tag(ps.telemetry.run_id)
        # Request tracing: sampled span contexts arrive as the binary
        # frame's trace extension or the X-Trace-Context header; every
        # handler contributes a SERVE child span (+ decode/render/
        # queue_wait/apply below it) on the server's own bus — the
        # collector stitches them back under the worker's root by
        # trace_id.
        tracer = _rpctrace.tracer_for(ps.telemetry)

        def _cached_body(fmt: str):
            """(version, body) from ONE slot read — the handler's
            freshness decision and the served bytes share a source of
            truth. Materialization and rendering happen UNDER the
            lock: when a new version lands and every worker pulls at
            once, late arrivals block briefly and reuse the one body
            instead of each paying the multi-second host download (and
            a slow dump can never overwrite a newer cached entry)."""
            with wire_lock:
                version, params = ps.slot.read()
                if wire_cache["version"] != version:
                    wire_cache.update(version=version,
                                      host=_to_host(params),
                                      dill=None, bin=None)
                if wire_cache[fmt] is None:
                    if fmt == "dill":
                        wire_cache["dill"] = dill.dumps(
                            (version, wire_cache["host"])
                        )
                    else:
                        wire_cache["bin"] = binwire.frame_bytes(
                            binwire.encode(wire_cache["host"],
                                           version=version,
                                           run_tag=server_tag)
                        )
                return version, wire_cache[fmt]

        psh = self
        shard_label = self.shard
        extra_json = self.extra_json_routes
        ring_version_fn = self.ring_version_fn

        def _record_wire(route: str, direction: str, nbytes: int,
                         seconds: float) -> None:
            """Per-route byte/latency accounting on the bus: the
            `/metrics` series the ISSUE names (wire_bytes_total plus a
            push/pull latency histogram per route). Fleet shards add
            a ``shard`` label so the per-shard series never alias."""
            labels = {"route": route, "dir": direction}
            hist_labels = {"route": route}
            if shard_label is not None:
                labels["shard"] = shard_label
                hist_labels["shard"] = shard_label
            ps.telemetry.counter("param_server.wire_bytes_total", nbytes,
                                 labels=labels)
            ps.telemetry.observe("param_server.wire_latency_s", seconds,
                                 labels=hist_labels)

        def _fire_shard_chaos(handler, route: str) -> bool:
            """The fleet's seeded shard-kill site: a chaos config can
            take THIS shard's HTTP frontend down at its Nth request.
            Returns True when the request must be aborted (connection
            dropped, no reply — exactly what a dying shard looks
            like from the client side)."""
            if shard_label is None:
                return False
            act = _chaos.fire("fleet.shard", shard=shard_label, route=route)
            if act and act.get("delay"):
                # Straggler-shard fault: the reply is correct, just
                # late. Slept BEFORE the route's serve span starts, so
                # a traced request sees it as the shard HOP's self
                # time (client-side `shard_pull` span) — network-shaped
                # latency lands on the hop, server work on `serve`, and
                # the critical path names this shard either way.
                time.sleep(float(act["delay"]))
            if act and act.get("die"):
                # stop() from a separate thread: it joins handler
                # machinery this very thread is part of.
                threading.Thread(target=psh.stop, daemon=True).start()
                handler.close_connection = True
                return True
            return False

        class Handler(BaseHTTPRequestHandler):
            # Keep-alive: binary transports hold ONE connection for a
            # whole training run instead of a TCP setup per call.
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # quiet, like werkzeug->ERROR
                pass  # (server.py:28-30 parity)

            def _send(self, code: int, body: bytes = b"",
                      content_type: Optional[str] = None,
                      extra_headers=None):
                self.send_response(code)
                if content_type:
                    self.send_header("Content-Type", content_type)
                for k, v in (extra_headers or {}).items():
                    self.send_header(k, str(v))
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                if body:
                    self.wfile.write(body)

            def _trace_ctx(self, raw: Optional[bytes] = None):
                """The request's span context: the binary frame's
                trace extension when a body is given (the push path —
                the frame is authoritative), else the HTTP header.
                None (untraced) on anything absent or malformed — a
                garbled context must never fail a request."""
                if raw:
                    try:
                        ctx = binwire.frame_trace(raw)
                    except binwire.WireError:
                        ctx = None
                    if ctx is not None:
                        return ctx
                return _rpctrace.SpanContext.from_header(
                    self.headers.get(_rpctrace.TRACE_HEADER))

            def _serve_span(self, route: str, ctx):
                ann = {"route": route}
                if shard_label is not None:
                    ann["shard"] = shard_label
                return tracer.child_span("serve", ctx, kind="server",
                                         **ann)

            def _delta_headers(self) -> dict:
                """Resync metadata on EVERY delta reply (304 too): the
                slot epoch catches rebuilt server state, the ring
                version catches shard add/drain."""
                out = {}
                epoch = getattr(ps.slot, "epoch", None)
                if epoch is not None:
                    out["X-Slot-Epoch"] = str(int(epoch))
                if ring_version_fn is not None:
                    out["X-Ring-Version"] = str(int(ring_version_fn()))
                return out

            def do_GET(self):
                route = self.path.split("?", 1)[0]
                if _fire_shard_chaos(self, route):
                    return
                ps.telemetry.counter("param_server.http_requests",
                                     labels={"route": route})
                if route == "/delta.bin" \
                        and hasattr(ps, "render_delta"):
                    with self._serve_span(route, self._trace_ctx()) as ssp:
                        t0 = time.perf_counter()  # lint-obs: ok (request-latency histogram clock pair, not a ledger region)
                        have = int(self.headers.get("X-Have-Version",
                                                    "-1"))
                        quant = self.headers.get("X-Pull-Quant") or None
                        try:
                            with tracer.child_span("render", ssp.ctx,
                                                   kind="server"):
                                _version, body = ps.render_delta(
                                    have, quant=quant,
                                    run_tag=server_tag
                                )
                        except ValueError:
                            self._send(400)
                            return
                        hdrs = self._delta_headers()
                        if body is None:
                            self._send(304, extra_headers=hdrs)
                            _record_wire(route, "tx", 0,
                                         time.perf_counter() - t0)  # lint-obs: ok (request-latency histogram clock pair, not a ledger region)
                            return
                        act = _chaos.fire("param_server.pull",
                                          route=route)
                        if act and act.get("truncate"):
                            body = body[: max(1, len(body) // 2)]
                        self._send(200, body,
                                   content_type=binwire.CONTENT_TYPE,
                                   extra_headers=hdrs)
                        _record_wire(route, "tx", len(body),
                                     time.perf_counter() - t0)  # lint-obs: ok (request-latency histogram clock pair, not a ledger region)
                    return
                if route in extra_json:
                    try:
                        doc = extra_json[route]()
                    except Exception:
                        self._send(500)
                        return
                    self._send(200, json.dumps(doc).encode(),
                               content_type="application/json")
                    return
                if route == "/":
                    self._send(200, b"sparktorch-tpu parameter server")
                elif route in ("/parameters", "/parameters.bin"):
                    with self._serve_span(route, self._trace_ctx()) as ssp:
                        t0 = time.perf_counter()  # lint-obs: ok (request-latency histogram clock pair, not a ledger region)
                        have = int(self.headers.get("X-Have-Version",
                                                    "-1"))
                        binary = route.endswith(".bin")
                        with tracer.child_span("render", ssp.ctx,
                                               kind="server"):
                            version, body = _cached_body(
                                "bin" if binary else "dill")
                        if version <= have:
                            # 304 on the binary wire (true HTTP
                            # semantics); the dill route keeps its
                            # original 204 so old clients stay
                            # byte-compatible.
                            self._send(304 if binary else 204)
                            _record_wire(route, "tx", 0,
                                         time.perf_counter() - t0)  # lint-obs: ok (request-latency histogram clock pair, not a ledger region)
                        else:
                            act = _chaos.fire("param_server.pull",
                                              route=route)
                            if act and act.get("truncate"):
                                # Injected torn response: the declared
                                # length is honest for the bytes sent,
                                # so the CLIENT'S frame check (WireError
                                # on a short payload) is what must
                                # catch it.
                                body = body[: max(1, len(body) // 2)]
                            self._send(200, body,
                                       content_type=binwire.CONTENT_TYPE
                                       if binary else None)
                            _record_wire(route, "tx", len(body),
                                         time.perf_counter() - t0)  # lint-obs: ok (request-latency histogram clock pair, not a ledger region)
                elif route == "/metrics":
                    text = render_prometheus(ps.telemetry.snapshot())
                    self._send(200, text.encode(),
                               content_type=PROMETHEUS_CONTENT_TYPE)
                elif route == "/telemetry":
                    self._send(200,
                               json.dumps(ps.telemetry.snapshot()).encode(),
                               content_type="application/json")
                else:
                    self._send(404)

            def do_POST(self):
                # Label with the query-stripped route (like do_GET):
                # raw paths would split one route across series and
                # let a client grow label cardinality without bound.
                route = self.path.split("?", 1)[0]
                if _fire_shard_chaos(self, route):
                    return
                ps.telemetry.counter("param_server.http_requests",
                                     labels={"route": route})
                length = int(self.headers.get("Content-Length", "0"))
                raw = self.rfile.read(length)
                if route == "/update":
                    with self._serve_span(route, self._trace_ctx()) as ssp:
                        t0 = time.perf_counter()  # lint-obs: ok (request-latency histogram clock pair, not a ledger region)
                        try:
                            # Chaos 500s fire here — inside the try, so
                            # the forced error takes the same path a
                            # real apply failure would (a 500, nothing
                            # else).
                            _chaos.fire("param_server.update",
                                        route=route)
                            with tracer.child_span("decode", ssp.ctx,
                                                   kind="server"):
                                grads = dill.loads(raw)
                            ps.push_gradients(grads, trace_ctx=ssp.ctx)
                            self._send(200, b"OK")
                            _record_wire(route, "rx", len(raw),
                                         time.perf_counter() - t0)  # lint-obs: ok (request-latency histogram clock pair, not a ledger region)
                        except Exception:
                            ssp.annotate(http_status=500)
                            self._send(500)
                elif route == "/update.bin":
                    with self._serve_span(route,
                                          self._trace_ctx(raw)) as ssp:
                        t0 = time.perf_counter()  # lint-obs: ok (request-latency histogram clock pair, not a ledger region)
                        try:
                            with tracer.child_span("decode", ssp.ctx,
                                                   kind="server"):
                                _version, grads = binwire.decode(raw)
                            frame_tag = binwire.frame_run_tag(raw)
                        except binwire.WireError:
                            # A malformed frame is the CLIENT's bug (or
                            # a truncated send): 400, and never counted
                            # against the server's tolerated apply
                            # errors.
                            ssp.annotate(http_status=400)
                            self._send(400)
                            return
                        if frame_tag and server_tag \
                                and frame_tag != server_tag:
                            ps.telemetry.counter(
                                "param_server.run_tag_mismatches_total"
                            )
                        try:
                            _chaos.fire("param_server.update",
                                        route=route)
                            ps.push_gradients(grads, trace_ctx=ssp.ctx)
                            self._send(200, b"OK")
                            _record_wire(route, "rx", len(raw),
                                         time.perf_counter() - t0)  # lint-obs: ok (request-latency histogram clock pair, not a ledger region)
                        except Exception:
                            ssp.annotate(http_status=500)
                            self._send(500)
                elif route == "/losses":
                    stop = ps.post_loss(dill.loads(raw))
                    self._send(200, dill.dumps({"stop": bool(stop)}))
                elif route == "/losses.json":
                    try:
                        loss = float(json.loads(raw)["loss"])
                    except (ValueError, KeyError, TypeError):
                        self._send(400)
                        return
                    stop = ps.post_loss(loss)
                    self._send(200,
                               json.dumps({"stop": bool(stop)}).encode(),
                               content_type="application/json")
                else:
                    self._send(404)

        self._httpd = _KeepAliveHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]  # resolve port 0
        self._thread = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        self._thread.start()
        return self

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            # Drop live keep-alive connections too: a stopped server
            # must go DARK (clients redial a restarted one), not keep
            # answering through parked handler threads.
            self._httpd.close_all_connections()
            self._httpd.server_close()
            self._httpd = None
