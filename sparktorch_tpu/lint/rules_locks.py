"""Lock-hold rule (SPK301): expensive work inside ``with <lock>:``.

The shipped bug class: PR 9's router computed latency percentiles while
holding the routing lock, and the telemetry bus's histogram roll-ups
did the same under the bus lock until PR 11 — every counter bump on
every thread waited on an O(4096) ``np.percentile``. The fixed idiom
(``obs.telemetry.rollup_from_state``) snapshots cheap state under the
lock and computes outside it. This rule flags calls that are expensive
by construction (percentiles, serialization, file/socket/HTTP IO,
sleeps, jit compiles, device transfers) lexically inside a with-block
whose context expression is lock-shaped.

Deliberate exceptions (e.g. a JSONL sink whose lock IS the file's
writer lock) carry ``# lint-obs: ok (<why>)`` on the call line.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, List, Optional

from sparktorch_tpu.lint.core import FileContext, Finding, Rule

# Last dotted component of the with-context expression: self._lock,
# lock, _bus_lock, routing_mutex...
_LOCK_NAME_RE = re.compile(r"(^|_)(lock|mutex)$", re.IGNORECASE)

# Canonical call targets that are expensive by construction.
_EXPENSIVE_EXACT = {
    "numpy.percentile", "numpy.quantile", "numpy.median", "numpy.sort",
    "json.dump", "json.dumps", "json.load", "json.loads",
    "time.sleep",
    "urllib.request.urlopen",
    "subprocess.run", "subprocess.check_output", "subprocess.check_call",
    "subprocess.Popen",
    "requests.get", "requests.post", "requests.request",
    "jax.jit", "jax.device_get", "jax.device_put",
    "open",
}

# Method names that are IO no matter the receiver (socket/HTTP waits).
_EXPENSIVE_ATTRS = {
    "recv", "recv_into", "sendall", "connect", "accept",
    "getresponse", "urlopen", "block_until_ready",
}


def _lock_like(ctx: FileContext, expr: ast.AST) -> Optional[str]:
    """Dotted name of a lock-shaped with-context expression, else None.
    Only bare Name/Attribute chains count — ``with Lock():`` creates a
    private lock nothing else contends on."""
    if not isinstance(expr, (ast.Name, ast.Attribute)):
        return None
    name = ctx.index.resolve(expr)
    if name is None:
        return None
    if _LOCK_NAME_RE.search(name.rsplit(".", 1)[-1]):
        return name
    return None


def _walk_immediate(body: List[ast.stmt]) -> Iterator[ast.AST]:
    """Walk statements recursively but never descend into nested
    function/lambda bodies — those run later, not under the lock."""
    stack: List[ast.AST] = list(body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


class LockHoldRule(Rule):
    id = "SPK301"
    slug = "lock-hold"
    summary = "expensive call while holding a lock"
    why = ("the PR 9/11 router/bus regression: percentile roll-ups "
           "computed under the hot-path lock serialized every reader; "
           "snapshot under the lock, compute outside")

    def run(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ctx.index.withs:
            lock_name = None
            for item in node.items:
                lock_name = _lock_like(ctx, item.context_expr)
                if lock_name:
                    break
            if not lock_name:
                continue
            for inner in _walk_immediate(node.body):
                if not isinstance(inner, ast.Call):
                    continue
                target = ctx.index.resolve(inner.func)
                expensive = (target in _EXPENSIVE_EXACT
                             if target is not None else False)
                if (not expensive and isinstance(inner.func, ast.Attribute)
                        and inner.func.attr in _EXPENSIVE_ATTRS):
                    expensive = True
                    target = inner.func.attr
                if expensive:
                    yield self.finding(
                        ctx, inner,
                        f"`{target}` called while holding `{lock_name}` "
                        f"— expensive work under a lock serializes "
                        f"every contender (the PR 9/11 percentile-"
                        f"under-the-bus-lock regression); snapshot "
                        f"under the lock and compute outside, or "
                        f"annotate `# lint-obs: ok (<why>)`")
