"""Observability-discipline rules (SPK101-108).

SPK101-105 are the AST migrations of the Makefile's historical
``lint-obs`` grep stanzas (print / bare span / json.dump / urllib
scraping / span-context minting); SPK106 encodes the
``Telemetry.event(kind=...)`` envelope-key collision the alerts WATCH
documented (the sink record envelope is ``{"ts", "kind", "run_id"}``
plus the collector's rank tag — a payload field with one of those
names silently overwrites the envelope); SPK107 fences the
interpreter's profiling hooks to ``obs/profile.py`` (the continuous
stack sampler owns them); SPK108 keeps device->host readbacks in the
trainers inside an attributed ledger span (the async-dispatch
discipline the health ledger's delayed fetch exists to preserve).
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from sparktorch_tpu.lint.core import FileContext, Finding, Rule


def _outside_obs(rel: Optional[str]) -> bool:
    return rel is None or not rel.startswith("obs/")


class ObsPrintRule(Rule):
    id = "SPK101"
    slug = "obs-print"
    summary = "raw print() in library code (use obs.log.get_logger)"
    why = ("the reference's print-based story (distributed.py:201-204) "
           "must not creep back in; structured telemetry goes through "
           "sparktorch_tpu.obs, human lines through obs.log.get_logger")

    # CLIs whose stdout is their contract (same set the grep excluded,
    # plus the analyzer's own CLI).
    EXEMPT = ("bench.py", "net/bench_wire.py", "obs/timeline.py",
              "obs/replay.py", "parallel/tune.py", "lint/cli.py")

    def applies(self, rel: Optional[str]) -> bool:
        return rel not in self.EXEMPT

    def run(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ctx.index.calls:
            if (isinstance(node.func, ast.Name)
                    and node.func.id == "print"):
                yield self.finding(
                    ctx, node,
                    "raw print() in library code: structured telemetry "
                    "goes through sparktorch_tpu.obs, human lines "
                    "through obs.log.get_logger")


class BareSpanRule(Rule):
    id = "SPK102"
    slug = "obs-bare-span"
    summary = "bare .span(...) call outside a with-block"
    why = ("a span only records when its with-block closes; a bare call "
           "leaks an un-timed region onto the thread-local stack and "
           "re-paths every nested span under it")

    def applies(self, rel: Optional[str]) -> bool:
        return _outside_obs(rel)

    def run(self, ctx: FileContext) -> Iterator[Finding]:
        idx = ctx.index
        for node in idx.calls:
            if not (isinstance(node.func, ast.Attribute)
                    and node.func.attr == "span"):
                continue
            if id(node) in idx.with_ctx or id(node) in idx.enter_ctx:
                continue
            yield self.finding(
                ctx, node,
                "bare .span(...) call: a span only records when its "
                "with-block closes — use `with ...span(...):` (or "
                "ExitStack.enter_context)")


class JsonDumpRule(Rule):
    id = "SPK103"
    slug = "obs-json-dump"
    summary = "raw json.dump outside obs/ (telemetry goes through sinks)"
    why = ("timeline data must flow through the obs sinks (atomicity, "
           "append semantics, scrape==dump parity); genuine "
           "non-telemetry persistence is annotated")

    def applies(self, rel: Optional[str]) -> bool:
        return _outside_obs(rel)

    def run(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ctx.index.calls:
            if ctx.index.resolve(node.func) == "json.dump":
                yield self.finding(
                    ctx, node,
                    "raw json.dump outside obs/: telemetry/trace events "
                    "go through the obs sinks; annotate genuine "
                    "non-telemetry persistence with "
                    "`# lint-obs: ok (<why>)`")


class UrllibScrapeRule(Rule):
    id = "SPK104"
    slug = "obs-urllib-scrape"
    summary = "ad-hoc urllib scraping outside obs/"
    why = ("readers of /metrics, /telemetry, /heartbeats, /gang go "
           "through obs.collector.scrape_json/scrape_text (shared "
           "timeout, error taxonomy, degradation discipline)")

    def applies(self, rel: Optional[str]) -> bool:
        return _outside_obs(rel)

    def run(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ctx.index.calls:
            if (ctx.index.resolve(node.func)
                    == "urllib.request.urlopen"):
                yield self.finding(
                    ctx, node,
                    "ad-hoc urllib.request.urlopen outside obs/: scrape "
                    "readers go through obs.collector.scrape_json/"
                    "scrape_text; annotate a non-scrape data wire with "
                    "`# lint-obs: ok (<why>)`")


class SpanContextMintRule(Rule):
    id = "SPK105"
    slug = "obs-span-context"
    summary = "RPC span context minted outside obs/"
    why = ("SpanContext construction belongs to obs/rpctrace.py's "
           "helpers (root_span/child_span/SpanContext.child/from_*), "
           "where sampling decisions, SLO forcing, and id entropy stay "
           "audited")

    def applies(self, rel: Optional[str]) -> bool:
        return _outside_obs(rel)

    def run(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ctx.index.calls:
            name = ctx.index.resolve(node.func)
            if name is not None and (name == "SpanContext"
                                     or name.endswith(".SpanContext")):
                yield self.finding(
                    ctx, node,
                    "span context minted outside obs/: go through the "
                    "obs.rpctrace tracer helpers (root_span/child_span/"
                    "SpanContext.child), or annotate "
                    "`# lint-obs: ok (<why>)`")


class ProfilerApiRule(Rule):
    id = "SPK107"
    slug = "profiler-api"
    summary = "interpreter profiling hook used outside obs/profile.py"
    why = ("sys.settrace/setprofile wreck jit dispatch for the whole "
           "process and a second sys._current_frames() walker "
           "double-pays the <1%-overhead budget bench-profile gates; "
           "stack sampling goes through obs.profile.StackProfiler, "
           "where rate, bounds, and bucket tagging stay audited")

    HOOKS = ("sys._current_frames", "sys.settrace", "sys.setprofile")

    def applies(self, rel: Optional[str]) -> bool:
        return rel != "obs/profile.py"

    def run(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ctx.index.calls:
            name = ctx.index.resolve(node.func)
            if name in self.HOOKS:
                yield self.finding(
                    ctx, node,
                    f"{name}() outside obs/profile.py: interpreter "
                    f"profiling hooks belong to the continuous stack "
                    f"sampler (obs.profile.StackProfiler) — sample "
                    f"through it, or annotate a genuine debug dump "
                    f"with `# lint-obs: ok (<why>)`")


class AsyncFetchRule(Rule):
    id = "SPK108"
    slug = "obs-async-fetch"
    summary = ("unattributed device sync (jax.device_get/"
               "block_until_ready) in train/")
    why = ("a raw readback in a trainer stalls the async dispatch "
           "pipeline AND hides the stall from the goodput ledger — the "
           "health ledger's delayed fetch exists so numerics readbacks "
           "land K steps late under data_wait{site=health}; any sync "
           "the trainers do must sit inside a ledger span so the time "
           "is attributed, not silently lost")

    SYNC_CALLS = ("jax.device_get", "jax.block_until_ready")
    SPAN_ATTRS = ("span", "step_span")
    # obs/skew.py is stamp-scope (see SPK201.STAMP_SCOPES): it merges
    # ledger stamps that were captured asynchronously, so a device sync
    # there would put wall time on the merge path of every scrape.
    EXTRA_SCOPES = ("obs/skew.py",)

    def applies(self, rel: Optional[str]) -> bool:
        return (rel is None or rel.startswith("train/")
                or rel.startswith(self.EXTRA_SCOPES))

    def _in_ledger_span(self, ctx: FileContext, node: ast.AST) -> bool:
        for anc in ctx.index.parent_chain(node):
            if not isinstance(anc, (ast.With, ast.AsyncWith)):
                continue
            for item in anc.items:
                expr = item.context_expr
                if (isinstance(expr, ast.Call)
                        and isinstance(expr.func, ast.Attribute)
                        and expr.func.attr in self.SPAN_ATTRS):
                    return True
        return False

    def run(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ctx.index.calls:
            name = ctx.index.resolve(node.func)
            is_sync = name in self.SYNC_CALLS or (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "block_until_ready")
            if not is_sync:
                continue
            if self._in_ledger_span(ctx, node):
                continue
            what = (name if name in self.SYNC_CALLS
                    else ".block_until_ready()")
            yield self.finding(
                ctx, node,
                f"{what} in a trainer outside a ledger span: a raw "
                f"device sync stalls dispatch and the stall is "
                f"invisible to the goodput ledger — wrap it in "
                f"`with ...span(...)`/`step_span(...)` (or feed the "
                f"health ledger, which fetches K steps late under "
                f"data_wait{{site=health}}), or annotate "
                f"`# lint-obs: ok (<why>)`")


class EventKindCollisionRule(Rule):
    id = "SPK106"
    slug = "event-kind-collision"
    summary = "reserved envelope key passed as an event payload field"
    why = ("sink records are `{ts, kind, run_id, **fields}` and the "
           "collector rank-tags them: a payload field named kind/ts/"
           "rank silently overwrites the envelope (the alerts "
           "`rule_kind` WATCH — Telemetry.event(kind=...) collides)")

    RESERVED = ("kind", "ts", "rank", "run_id")

    def run(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ctx.index.calls:
            if not (isinstance(node.func, ast.Attribute)
                    and node.func.attr == "event"):
                continue
            for kw in node.keywords:
                if kw.arg in self.RESERVED:
                    yield self.finding(
                        ctx, kw.value,
                        f"reserved record key `{kw.arg}=` passed as an "
                        f"event payload field: the sink envelope owns "
                        f"{{ts, kind, run_id}} and the collector owns "
                        f"the rank tag — prefix the field "
                        f"(e.g. rule_kind) instead",
                        line=kw.value.lineno)
