"""JAX hazard rules (SPK401 retrace-hazard, SPK402 collective-context).

SPK401 encodes the recompile-tax class PR 14 chased at runtime: a
jitted callable invoked with Python scalars derived from values that
vary per call (``len(...)``, ``range``/``enumerate`` loop indices)
keys a fresh compile-cache entry per distinct value — whether the
scalar is shape-affecting (must be static, retraces per value) or
accidentally traced (silently weak-typed). Either way it is a per-call
compile-key decision that must be explicit (``static_argnums`` or
hashed into the traced batch). The second shape: a jitted function
closing over a *mutable module global* — the traced value is baked at
the first compile, so later mutation is silently ignored (or, with
``static_argnums``-style hashing, retraces).

SPK402 encodes PR 12's MoE root-cause (a): on jax 0.4.x the GSPMD
partitioner silently drops layout constraints, so a collective whose
literal ``axis_name`` is not bound by an enclosing ``shard_map``/
``pmap`` scope is either a trace-time error waiting for a code path or
— worse — a constraint the partitioner rewrites into token-replicating
all-gathers. Collectives whose axis comes in as a *parameter* are the
caller's obligation and are skipped (``ops.attention.ring_attention``'s
contract); literal-axis collectives must be reachable, within the
module, from a function handed to ``shard_map``/``shard_map_compat``/
``pmap`` (or registered via ``.defvjp`` — a custom-VJP fwd/bwd runs
wherever its primal runs).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from sparktorch_tpu.lint.core import FileContext, Finding, Rule

_JIT_NAMES = {"jax.jit", "jit"}


def _is_jit_call(ctx: FileContext, node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and ctx.index.resolve(node.func) in _JIT_NAMES)


def _static_decls(call: ast.Call) -> Tuple[Set[int], Set[str]]:
    """(static_argnums, static_argnames) declared on a jax.jit call,
    as far as they are literal."""
    nums: Set[int] = set()
    names: Set[str] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            vals = (kw.value.elts
                    if isinstance(kw.value, (ast.Tuple, ast.List))
                    else [kw.value])
            for v in vals:
                if isinstance(v, ast.Constant) and isinstance(v.value, int):
                    nums.add(v.value)
        elif kw.arg == "static_argnames":
            vals = (kw.value.elts
                    if isinstance(kw.value, (ast.Tuple, ast.List))
                    else [kw.value])
            for v in vals:
                if isinstance(v, ast.Constant) and isinstance(v.value, str):
                    names.add(v.value)
    return nums, names


class RetraceHazardRule(Rule):
    id = "SPK401"
    slug = "retrace-hazard"
    summary = "jitted call keyed on a per-call-varying Python scalar"
    why = ("the PR 14 recompile-tax class: every distinct Python scalar "
           "reaching a jit boundary is a compile-cache key decision; "
           "len()/loop-index arguments make it silently per-call")

    def run(self, ctx: FileContext) -> Iterator[Finding]:
        yield from self._varying_scalar_args(ctx)
        yield from self._mutable_global_closures(ctx)

    # -- jitted calls fed len(...) / loop indices -----------------------
    def _varying_scalar_args(self, ctx: FileContext) -> Iterator[Finding]:
        idx = ctx.index
        # name -> (static_argnums, static_argnames) for `f = jax.jit(..)`
        jitted: Dict[str, Tuple[Set[int], Set[str]]] = {}
        for node in idx.assigns:
            if (len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and _is_jit_call(ctx, node.value)):
                jitted[node.targets[0].id] = _static_decls(node.value)
        if not jitted:
            return
        # Integer-ish loop variables: `for i in range(...)` /
        # `for i, x in enumerate(...)` — keyed by the For node that
        # binds them, so a same-named parameter in another function is
        # never mistaken for a loop index (an arg counts only when the
        # call site is lexically inside the binding loop).
        loop_vars: Dict[str, List[ast.AST]] = {}
        for node in idx.fors:
            it = node.iter
            src = (idx.resolve(it.func)
                   if isinstance(it, ast.Call) else None)
            if src == "range":
                if isinstance(node.target, ast.Name):
                    loop_vars.setdefault(node.target.id, []).append(node)
            elif src == "enumerate":
                if (isinstance(node.target, ast.Tuple) and node.target.elts
                        and isinstance(node.target.elts[0], ast.Name)):
                    loop_vars.setdefault(
                        node.target.elts[0].id, []).append(node)

        def varying(arg: ast.AST) -> Optional[str]:
            if (isinstance(arg, ast.Call) and isinstance(arg.func, ast.Name)
                    and arg.func.id == "len"):
                return "len(...)"
            if isinstance(arg, ast.Name) and arg.id in loop_vars:
                binders = loop_vars[arg.id]
                if any(p in binders for p in idx.parent_chain(arg)):
                    return f"loop index `{arg.id}`"
            return None

        for node in idx.calls:
            if not (isinstance(node.func, ast.Name)
                    and node.func.id in jitted):
                continue
            nums, names = jitted[node.func.id]
            for i, arg in enumerate(node.args):
                desc = varying(arg)
                if desc and i not in nums:
                    yield self.finding(
                        ctx, arg,
                        f"{desc} passed to jitted `{node.func.id}` at "
                        f"position {i} without a static_argnums "
                        f"declaration — a per-call-varying Python "
                        f"scalar is a silent compile-cache key (PR 14 "
                        f"recompile tax); declare it static or fold it "
                        f"into the traced batch")
            for kw in node.keywords:
                desc = varying(kw.value) if kw.arg else None
                if desc and kw.arg not in names:
                    yield self.finding(
                        ctx, kw.value,
                        f"{desc} passed to jitted `{node.func.id}` as "
                        f"`{kw.arg}=` without a static_argnames "
                        f"declaration — a per-call-varying Python "
                        f"scalar is a silent compile-cache key (PR 14 "
                        f"recompile tax)")

    # -- jitted closures over mutable module globals --------------------
    def _mutable_global_closures(self, ctx: FileContext) -> Iterator[Finding]:
        idx = ctx.index
        mutable: Set[str] = set()
        for stmt in ctx.tree.body:
            if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)):
                v = stmt.value
                if isinstance(v, (ast.List, ast.Dict, ast.Set)):
                    mutable.add(stmt.targets[0].id)
                elif (isinstance(v, ast.Call)
                        and idx.resolve(v.func) in ("dict", "list", "set")):
                    mutable.add(stmt.targets[0].id)
        if not mutable:
            return
        mutated: Set[str] = set()
        _MUTATORS = {"append", "update", "pop", "clear", "extend",
                     "setdefault", "add", "remove", "insert"}
        for g in idx.globals_:
            mutated.update(n for n in g.names if n in mutable)
        for node in idx.subscripts:
            if (isinstance(node.ctx, (ast.Store, ast.Del))
                    and isinstance(node.value, ast.Name)
                    and node.value.id in mutable):
                mutated.add(node.value.id)
        for node in idx.calls:
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr in _MUTATORS
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id in mutable):
                mutated.add(node.func.value.id)
        if not mutated:
            return
        for fn in self._jitted_defs(ctx):
            local: Set[str] = {a.arg for a in fn.args.args
                               + fn.args.posonlyargs + fn.args.kwonlyargs}
            for node in ast.walk(fn):
                if (isinstance(node, ast.Name)
                        and isinstance(node.ctx, ast.Store)):
                    local.add(node.id)
            for node in ast.walk(fn):
                if (isinstance(node, ast.Name)
                        and isinstance(node.ctx, ast.Load)
                        and node.id in mutated and node.id not in local):
                    yield self.finding(
                        ctx, node,
                        f"jitted `{fn.name}` closes over mutable module "
                        f"global `{node.id}` — the traced value is "
                        f"baked at the first compile; later mutation "
                        f"is silently ignored (PR 14 recompile-tax "
                        f"class). Pass it as an argument instead")

    def _jitted_defs(self, ctx: FileContext) -> Iterator[ast.FunctionDef]:
        idx = ctx.index
        defs: Dict[str, ast.FunctionDef] = {}
        seen: Set[int] = set()
        for node in idx.funcdefs:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs[node.name] = node
        for node in idx.funcdefs:
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for dec in node.decorator_list:
                if (idx.resolve(dec) in _JIT_NAMES
                        or _is_jit_call(ctx, dec)):
                    if id(node) not in seen:
                        seen.add(id(node))
                        yield node
        # `g = jax.jit(f)` over a module-level def.
        for node in idx.calls:
            if (_is_jit_call(ctx, node) and node.args
                    and isinstance(node.args[0], ast.Name)
                    and node.args[0].id in defs):
                fn = defs[node.args[0].id]
                if id(fn) not in seen:
                    seen.add(id(fn))
                    yield fn


_COLLECTIVES = {
    "jax.lax.psum", "jax.lax.pmean", "jax.lax.pmax", "jax.lax.pmin",
    "jax.lax.all_gather", "jax.lax.all_to_all", "jax.lax.ppermute",
    "jax.lax.psum_scatter", "jax.lax.axis_index",
}
# Position of the axis-name argument when passed positionally.
_AXIS_POS = {name: (0 if name.endswith("axis_index") else 1)
             for name in _COLLECTIVES}
_WRAPPER_LAST = {"shard_map", "shard_map_compat", "pmap", "xmap"}


class CollectiveContextRule(Rule):
    id = "SPK402"
    slug = "collective-context"
    summary = "literal-axis collective outside any shard_map/pmap scope"
    why = ("PR 12 MoE root-cause (a): the GSPMD partitioner silently "
           "drops unapplied constraints and derives token-replicating "
           "all-gathers; a literal axis_name must be bound by a "
           "shard_map/pmap the module can show")

    def run(self, ctx: FileContext) -> Iterator[Finding]:
        idx = ctx.index
        # Named callables: defs plus name-assigned lambdas.
        named: Dict[str, List[ast.AST]] = {}
        for node in idx.funcdefs:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                named.setdefault(node.name, []).append(node)
        for node in idx.assigns:
            if (len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Lambda)):
                named.setdefault(node.targets[0].id, []).append(node.value)

        # Name loads per immediate scope, for the propagation pass.
        loads_in_scope: Dict[int, Set[str]] = {}
        for node in idx.names:
            if isinstance(node.ctx, ast.Load):
                scope = idx.scope_of.get(id(node))
                loads_in_scope.setdefault(id(scope), set()).add(node.id)

        bound: Set[int] = set()  # id() of bound function-ish nodes
        pending: List[ast.AST] = []

        def bind(fn_node: ast.AST) -> None:
            if id(fn_node) not in bound:
                bound.add(id(fn_node))
                pending.append(fn_node)
                # Lexically nested defs execute under the same mapped
                # scope when called from it.
                for child in idx.scope_children.get(id(fn_node), []):
                    bind(child)

        for node in idx.calls:
            name = idx.resolve(node.func)
            last = name.rsplit(".", 1)[-1] if name else (
                node.func.attr if isinstance(node.func, ast.Attribute)
                else None)
            if last in _WRAPPER_LAST and node.args:
                arg0 = node.args[0]
                if isinstance(arg0, ast.Lambda):
                    bind(arg0)
                elif isinstance(arg0, ast.Name):
                    for d in named.get(arg0.id, []):
                        bind(d)
            elif last == "defvjp":
                # fwd/bwd run wherever their primal runs; the primal's
                # own binding is checked on its own collectives.
                for arg in node.args:
                    if isinstance(arg, ast.Name):
                        for d in named.get(arg.id, []):
                            bind(d)

        # Propagate: a bound function binds every module function whose
        # name its body references (call, pass-through, dict dispatch).
        while pending:
            fn = pending.pop()
            for ref in loads_in_scope.get(id(fn), ()):
                for d in named.get(ref, []):
                    bind(d)

        for node in idx.calls:
            name = idx.resolve(node.func)
            if name not in _COLLECTIVES:
                continue
            axis = self._axis_expr(node, name)
            literal = self._literal_axis(ctx, axis)
            if literal is None:
                continue  # parameterized/unresolvable: caller's contract
            if any(id(fn) in bound
                   for fn in idx.enclosing_functions(node)):
                continue
            yield self.finding(
                ctx, node,
                f"`{name.rsplit('.', 1)[-1]}` over literal axis "
                f"{literal!r} outside any shard_map/pmap-bound scope in "
                f"this module — under GSPMD the partitioner silently "
                f"drops the constraint and derives replicating "
                f"collectives (PR 12 MoE root-cause); wrap the caller "
                f"in shard_map or take the axis as a parameter")

    @staticmethod
    def _axis_expr(call: ast.Call, name: str) -> Optional[ast.AST]:
        for kw in call.keywords:
            if kw.arg == "axis_name":
                return kw.value
        pos = _AXIS_POS[name]
        if len(call.args) > pos:
            return call.args[pos]
        return None

    def _literal_axis(self, ctx: FileContext,
                      axis: Optional[ast.AST]) -> Optional[str]:
        """The literal axis-name string (or tuple repr) when the
        expression is a constant / module string constant / tuple of
        those; None when parameterized or unresolvable."""
        if axis is None:
            return None
        if isinstance(axis, ast.Constant) and isinstance(axis.value, str):
            return axis.value
        if isinstance(axis, ast.Name):
            return ctx.index.str_consts.get(axis.id)
        if isinstance(axis, (ast.Tuple, ast.List)):
            parts = [self._literal_axis(ctx, e) for e in axis.elts]
            if all(p is not None for p in parts):
                return "(" + ", ".join(parts) + ")"  # type: ignore[arg-type]
        return None
