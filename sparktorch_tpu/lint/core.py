"""sparklint core — shared AST machinery for the rule passes.

The analyzer exists because three regressions that actually shipped
here (percentile roll-ups computed while holding the bus lock, the
``Telemetry.event(kind=...)`` envelope collision, the use-after-free on
a stopped ``GangCoordinator`` handle) were all statically detectable,
and the Makefile's grep stanzas could see none of them: grep has no
notion of a with-block body, a call's argument list, or the scope a
name was stopped in. Every rule here is AST-based, carries a stable ID
(``SPK...``), and honors the per-line ``# lint-obs: ok (<why>)``
annotation convention the greps established.

Layout: this module owns ``Finding``, ``Rule``, ``ModuleIndex`` (the
per-file resolution index every rule shares) and ``run_lint`` (the
file walker). The rules themselves live in ``rules_*.py`` siblings and
register through ``sparktorch_tpu.lint.ALL_RULES``.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

# The suppression marker shared with the historical grep lints: a
# finding on a line carrying it (or on a line whose previous line is a
# pure comment carrying it) is accepted as a documented exception.
SUPPRESS_RE = re.compile(r"lint-obs:\s*ok\b")

PARSE_RULE_ID = "SPK000"
PARSE_RULE_SLUG = "parse-error"

PACKAGE_NAME = "sparktorch_tpu"


@dataclass(frozen=True)
class Finding:
    """One rule violation at one source location."""

    rule: str
    slug: str
    path: str
    line: int
    col: int
    message: str
    snippet: str = ""

    def to_dict(self) -> Dict[str, object]:
        return {
            "rule": self.rule,
            "slug": self.slug,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "snippet": self.snippet,
        }

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: "
                f"{self.rule} [{self.slug}] {self.message}")


_SCOPE_TYPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


class ModuleIndex:
    """Per-file resolution index shared by every rule.

    Built in ONE traversal per parsed module (the rules iterate the
    typed node buckets instead of re-walking the tree — the analyzer's
    wall-time gate depends on this): parent links, a scope map
    (innermost enclosing function/lambda per node), an import-alias
    map so ``np.percentile`` and ``from numpy import percentile`` both
    resolve to ``numpy.percentile``, module-level string constants
    (mesh axis names like ``AXIS_EP = "ep"``), and the set of calls
    that are with-block context expressions (what the bare-span grep
    could never see across line breaks).
    """

    def __init__(self, tree: ast.Module) -> None:
        self.tree = tree
        self.parents: Dict[ast.AST, ast.AST] = {}
        self.aliases: Dict[str, str] = {}
        self.str_consts: Dict[str, str] = {}
        self.with_ctx: Set[int] = set()
        self.enter_ctx: Set[int] = set()
        # Typed buckets, filled by the single traversal below.
        self.calls: List[ast.Call] = []
        self.withs: List[ast.AST] = []
        self.funcdefs: List[ast.AST] = []
        self.assigns: List[ast.Assign] = []
        self.attributes: List[ast.Attribute] = []
        self.names: List[ast.Name] = []
        self.fors: List[ast.AST] = []
        self.globals_: List[ast.Global] = []
        self.subscripts: List[ast.Subscript] = []
        # id(node) -> innermost enclosing FunctionDef/Lambda (None at
        # module level); scope_parent chains scopes outward.
        self.scope_of: Dict[int, Optional[ast.AST]] = {}
        self.scope_parent: Dict[int, Optional[ast.AST]] = {}
        self.scope_children: Dict[int, List[ast.AST]] = {}

        stack: List[Tuple[ast.AST, Optional[ast.AST]]] = [(tree, None)]
        while stack:
            node, scope = stack.pop()
            self.scope_of[id(node)] = scope
            child_scope = scope
            if isinstance(node, _SCOPE_TYPES):
                self.funcdefs.append(node)
                self.scope_parent[id(node)] = scope
                self.scope_children.setdefault(id(scope), []).append(node)
                child_scope = node
            elif isinstance(node, ast.Call):
                self.calls.append(node)
                if (isinstance(node.func, ast.Attribute)
                        and node.func.attr == "enter_context"):
                    for arg in node.args:
                        self.enter_ctx.add(id(arg))
            elif isinstance(node, (ast.With, ast.AsyncWith)):
                self.withs.append(node)
                for item in node.items:
                    self.with_ctx.add(id(item.context_expr))
            elif isinstance(node, ast.Assign):
                self.assigns.append(node)
            elif isinstance(node, ast.Attribute):
                self.attributes.append(node)
            elif isinstance(node, ast.Name):
                self.names.append(node)
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                self.fors.append(node)
            elif isinstance(node, ast.Global):
                self.globals_.append(node)
            elif isinstance(node, ast.Subscript):
                self.subscripts.append(node)
            elif isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        self.aliases[a.asname] = a.name
            elif isinstance(node, ast.ImportFrom) and node.level == 0:
                mod = node.module or ""
                for a in node.names:
                    if a.name == "*":
                        continue
                    full = f"{mod}.{a.name}" if mod else a.name
                    self.aliases[a.asname or a.name] = full
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node
                stack.append((child, child_scope))
        for stmt in tree.body:
            if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and isinstance(stmt.value, ast.Constant)
                    and isinstance(stmt.value.value, str)):
                self.str_consts[stmt.targets[0].id] = stmt.value.value

    def resolve(self, node: ast.AST) -> Optional[str]:
        """Canonical dotted path for a Name/Attribute chain, resolving
        import aliases (``np.percentile`` -> ``numpy.percentile``,
        ``perf_counter`` -> ``time.perf_counter``). ``self._lock``
        resolves literally. Non-name bases (calls, subscripts) resolve
        to None."""
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        parts.append(self.aliases.get(node.id, node.id))
        return ".".join(reversed(parts))

    def parent_chain(self, node: ast.AST) -> Iterator[ast.AST]:
        cur = self.parents.get(node)
        while cur is not None:
            yield cur
            cur = self.parents.get(cur)

    def enclosing_functions(self, node: ast.AST) -> List[ast.AST]:
        """Innermost-first FunctionDef/Lambda chain around a node."""
        chain: List[ast.AST] = []
        scope = self.scope_of.get(id(node))
        while scope is not None:
            chain.append(scope)
            scope = self.scope_parent.get(id(scope))
        return chain


@dataclass
class FileContext:
    path: str
    rel: Optional[str]  # package-relative path ("obs/telemetry.py"), or
    # None for files outside the sparktorch_tpu package (fixtures): rules
    # then apply with no path scoping so fixture files exercise them all.
    tree: ast.Module
    lines: List[str]
    index: ModuleIndex

    def snippet(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()[:160]
        return ""


class Rule:
    """Base class: subclasses set ``id``/``slug``/``summary``/``why``
    (the shipped bug class that motivated the rule) and implement
    ``run``. ``applies`` scopes by package-relative path — the same
    scoping the grep stanzas encoded with ``grep -v`` path filters."""

    id: str = ""
    slug: str = ""
    summary: str = ""
    why: str = ""

    def applies(self, rel: Optional[str]) -> bool:
        return True

    def run(self, ctx: FileContext) -> Iterator[Finding]:
        raise NotImplementedError

    def finding(self, ctx: FileContext, node: ast.AST,
                message: str, line: Optional[int] = None) -> Finding:
        ln = line if line is not None else getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        return Finding(rule=self.id, slug=self.slug, path=ctx.path,
                       line=ln, col=col, message=message,
                       snippet=ctx.snippet(ln))


def package_rel(path: str) -> Optional[str]:
    """Path relative to the innermost ``sparktorch_tpu`` package dir,
    or None when the file is outside the package (then no path scoping
    applies — fixture files must exercise every rule)."""
    parts = os.path.normpath(os.path.abspath(path)).split(os.sep)
    if PACKAGE_NAME not in parts:
        return None
    i = len(parts) - 1 - parts[::-1].index(PACKAGE_NAME)
    rel = "/".join(parts[i + 1:])
    return rel or None


def iter_py_files(paths: Sequence[str]) -> Iterator[str]:
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d != "__pycache__"
                                 and not d.startswith("."))
                for f in sorted(files):
                    if f.endswith(".py"):
                        yield os.path.join(root, f)
        elif p.endswith(".py"):
            yield p


def _suppressed(finding: Finding, lines: List[str]) -> bool:
    ln = finding.line
    if 1 <= ln <= len(lines) and SUPPRESS_RE.search(lines[ln - 1]):
        return True
    if ln >= 2:
        prev = lines[ln - 2].lstrip()
        if prev.startswith("#") and SUPPRESS_RE.search(prev):
            return True
    return False


def lint_file(path: str, rules: Sequence[Rule]) -> List[Finding]:
    try:
        with open(path, "r", encoding="utf-8") as f:
            source = f.read()
    except OSError as exc:
        # An unreadable file is a finding, not a crash: the CLI's
        # exit-code/--json/--log contract must survive it.
        return [Finding(rule=PARSE_RULE_ID, slug=PARSE_RULE_SLUG,
                        path=path, line=1, col=0,
                        message=f"could not read: {exc}")]
    lines = source.splitlines()
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as exc:
        return [Finding(rule=PARSE_RULE_ID, slug=PARSE_RULE_SLUG,
                        path=path, line=exc.lineno or 1,
                        col=exc.offset or 0,
                        message=f"could not parse: {exc.msg}")]
    rel = package_rel(path)
    ctx = FileContext(path=path, rel=rel, tree=tree, lines=lines,
                      index=ModuleIndex(tree))
    findings: List[Finding] = []
    for rule in rules:
        if not rule.applies(rel):
            continue
        findings.extend(f for f in rule.run(ctx)
                        if not _suppressed(f, lines))
    return findings


def run_lint(paths: Sequence[str],
             rules: Sequence[Rule]) -> Tuple[List[Finding], int]:
    """Lint every .py file under ``paths``; returns (findings sorted by
    location, files scanned)."""
    findings: List[Finding] = []
    n_files = 0
    for path in iter_py_files(paths):
        n_files += 1
        findings.extend(lint_file(path, rules))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings, n_files
