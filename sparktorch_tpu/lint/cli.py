"""sparklint CLI (this file's stdout is its contract, like
obs/timeline.py — it is print-rule-exempt by path).

Exit codes: 0 clean, 1 findings (or a --gate-wall breach), 2 usage
error (unknown rule). --json emits the machine schema (version-
stamped; golden-tested); --log appends one JSONL record per run so
``benchmarks/`` retains the analyzer's wall-time trend, and
--gate-wall FAILS the run when the analysis wall (parse+rules, not
interpreter startup — the package import bill is jax's, not ours)
exceeds the bound, so the lint step can never quietly become the
suite's slowest.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import List, Optional, Sequence

from sparktorch_tpu.lint import ALL_RULES, rules_by_selector
from sparktorch_tpu.lint.core import run_lint

JSON_SCHEMA_VERSION = 1


def _default_paths() -> List[str]:
    # Lint the installed package when no path is given.
    return [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m sparktorch_tpu.lint",
        description="sparklint: AST rules for this repo's shipped bug "
                    "classes. Suppress a documented exception with "
                    "`# lint-obs: ok (<why>)` on the finding's line.")
    parser.add_argument("paths", nargs="*", default=None,
                        help="files/dirs to lint (default: the "
                             "sparktorch_tpu package)")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable findings on stdout")
    parser.add_argument("--rule", action="append", default=[],
                        metavar="ID",
                        help="run only this rule (ID or slug; "
                             "repeatable)")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule table and exit")
    parser.add_argument("--gate-wall", type=float, default=None,
                        metavar="S",
                        help="fail if the analysis wall exceeds S "
                             "seconds")
    parser.add_argument("--log", default=None, metavar="PATH",
                        help="append one JSONL run record to PATH")
    args = parser.parse_args(argv)

    if args.list_rules:
        for r in ALL_RULES:
            print(f"{r.id}  {r.slug:22s} {r.summary}")
        return 0

    try:
        rules = rules_by_selector(args.rule)
    except KeyError as exc:
        known = ", ".join(f"{r.id}/{r.slug}" for r in ALL_RULES)
        print(f"unknown rule: {exc.args[0]} (known: {known})",
              file=sys.stderr)
        return 2

    paths = args.paths or _default_paths()
    missing = [p for p in paths if not os.path.exists(p)]
    if missing:
        print(f"no such path: {', '.join(missing)}", file=sys.stderr)
        return 2
    t0 = time.perf_counter()
    findings, n_files = run_lint(paths, rules)
    wall_s = time.perf_counter() - t0
    if n_files == 0:
        # A gate that scans nothing must never read as green — a path
        # typo in the Makefile would otherwise disarm the tier-1
        # prerequisite forever.
        print(f"no .py files found under: {', '.join(paths)}",
              file=sys.stderr)
        return 2

    counts: dict = {}
    for f in findings:
        counts[f.rule] = counts.get(f.rule, 0) + 1
    gate_ok = args.gate_wall is None or wall_s <= args.gate_wall

    if args.json:
        doc = {
            "version": JSON_SCHEMA_VERSION,
            "files_scanned": n_files,
            "wall_s": round(wall_s, 4),
            "rules": [r.id for r in rules],
            "counts": counts,
            "findings": [f.to_dict() for f in findings],
        }
        print(json.dumps(doc, indent=2, sort_keys=True))
    else:
        for f in findings:
            print(f.render())
        status = "clean" if not findings else f"{len(findings)} finding(s)"
        print(f"sparklint: {n_files} file(s), {len(rules)} rule(s), "
              f"{status}, {wall_s:.2f}s")

    if not gate_ok:
        print(f"sparklint: analysis wall {wall_s:.2f}s exceeds "
              f"--gate-wall {args.gate_wall:.2f}s", file=sys.stderr)

    if args.log:
        from sparktorch_tpu.obs.telemetry import wall_ts
        record = {
            "ts": wall_ts(),
            "config": "lint",
            "files": n_files,
            "findings": len(findings),
            "counts": counts,
            "wall_s": round(wall_s, 4),
            "gate_wall_s": args.gate_wall,
            "ok": bool(gate_ok and not findings),
        }
        os.makedirs(os.path.dirname(args.log) or ".", exist_ok=True)
        with open(args.log, "a", encoding="utf-8") as f:  # lint-obs: ok (bench record retention, not telemetry)
            f.write(json.dumps(record) + "\n")

    return 0 if (gate_ok and not findings) else 1
