"""``python -m sparktorch_tpu.lint`` entry point."""

import sys

from sparktorch_tpu.lint.cli import main

sys.exit(main())
