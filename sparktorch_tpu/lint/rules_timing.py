"""Timing-ledger rule (SPK201): raw clock reads outside the sanctioned
idioms.

AST replacement for the Makefile's two clock grep bans, with the two
holes they had closed: import aliasing (``from time import
perf_counter`` / ``import time as t`` were invisible to the grep) and
line-break evasion. The contract (README "Goodput ledger"):

- DURATION math uses ``time.perf_counter()`` — the wall clock steps
  under NTP slew and a negative "latency" has bitten this repo;
  genuine wall-clock TIMESTAMPS go through the named helper
  ``obs.telemetry.wall_ts()`` so the two stay distinguishable.
- In the ledger-covered packages (train/, ctl/, parallel/, serve/)
  even ``perf_counter`` is not free: measured regions go through
  ``obs.goodput`` LedgerSpans (``goodput.span``/``step_span``, read
  ``.duration_s``) so the run-level time ledger stays MECE. Control-
  flow clocks (deadlines, backoff, throttles) annotate
  ``# lint-obs: ok (<why>)``.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from sparktorch_tpu.lint.core import FileContext, Finding, Rule


class TimingLedgerRule(Rule):
    id = "SPK201"
    slug = "timing-ledger"
    summary = "raw clock read outside the wall_ts/LedgerSpan idioms"
    why = ("PR 13 converted 43 raw-clock sites so every measured second "
           "lands in exactly one goodput bucket; a raw clock in a "
           "ledger-covered package is either an unattributed measured "
           "region or an NTP-vulnerable duration")

    # perf_counter is banned (outside LedgerSpans) only where the
    # goodput ledger owns time attribution.
    LEDGER_SCOPES = ("train/", "ctl/", "parallel/", "serve/")

    # Stamp scope: modules that derive cross-rank step-boundary stamps
    # from the ledger's span clock. Here BOTH clocks are banned — a
    # local clock read would create a second time base that cannot be
    # aligned across ranks (the skew merge subtracts stamps from
    # different hosts; only ledger-anchored stamps share an epoch).
    STAMP_SCOPES = ("obs/skew.py",)

    def run(self, ctx: FileContext) -> Iterator[Finding]:
        rel = ctx.rel
        in_stamp_scope = rel is not None and rel.startswith(self.STAMP_SCOPES)
        in_obs = (rel is not None and rel.startswith("obs/")
                  and not in_stamp_scope)
        in_ledger_scope = rel is None or rel.startswith(self.LEDGER_SCOPES)
        for node in ctx.index.calls:
            name = ctx.index.resolve(node.func)
            if name == "time.time" and not in_obs:
                if in_stamp_scope:
                    yield self.finding(
                        ctx, node,
                        "raw time.time() in a stamp-scope module: skew "
                        "step-boundary stamps must come from the "
                        "ledger's span clock (GoodputLedger stamps "
                        "inside step_span; obs/skew.py only does "
                        "arithmetic over them), or annotate "
                        "`# lint-obs: ok (<why>)`")
                else:
                    yield self.finding(
                        ctx, node,
                        "raw time.time(): durations must use "
                        "time.perf_counter(); wall-clock timestamps go "
                        "through obs.telemetry.wall_ts(), or annotate "
                        "`# lint-obs: ok (<why>)`")
            elif name == "time.perf_counter" and in_stamp_scope:
                yield self.finding(
                    ctx, node,
                    "raw perf_counter in a stamp-scope module: skew "
                    "step-boundary stamps must come from the ledger's "
                    "span clock (LedgerSpan captures enter/exit once "
                    "inside step_span) — a second clock read here "
                    "cannot be aligned across ranks; annotate "
                    "`# lint-obs: ok (<why>)`")
            elif name == "time.perf_counter" and in_ledger_scope:
                yield self.finding(
                    ctx, node,
                    "raw perf_counter timing in a ledger-covered "
                    "package: measured regions go through obs.goodput "
                    "LedgerSpans (goodput.span/step_span, read "
                    ".duration_s) so the run ledger stays MECE; "
                    "annotate a control-flow clock with "
                    "`# lint-obs: ok (<why>)`")
