"""Handle-lifecycle rule (SPK501): native-handle access after stop/kill.

The shipped bug: PR 10's elastic bench read ``coord.generation`` after
the ``finally: coord.stop()`` had freed the native gang state — a
use-after-free that segfaulted the whole bench process. The fix
snapshotted final state *before* the free; the rule keeps the class
out: within one function scope, attribute access on a native handle
(``GangCoordinator``, ``ProcessWorker``, anything from
``spawn_worker``) after ``.stop()``/``.kill()`` on the same name, with
no reassignment in between, is flagged unless the attribute is in the
documented post-stop-safe set (supervisor contract: ``error``,
``is_alive``, ``join``...). Reads of snapshot properties that are
*designed* to survive stop carry ``# lint-obs: ok (<why>)``.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Tuple

from sparktorch_tpu.lint.core import FileContext, Finding, Rule

# Constructors whose results hold native/process state that dies with
# stop()/kill().
_HANDLE_CTORS = {"GangCoordinator", "ProcessWorker", "spawn_worker"}

# The supervisor handle contract: these stay valid after stop/kill
# (pure-Python side: exit decoding, liveness polling, idempotent
# re-stop, payload cleanup).
_SAFE_AFTER_STOP = {
    "stop", "kill", "join", "is_alive", "cleanup", "error", "name",
    "returncode", "exitcode", "rank",
}


def _base_name(node: ast.AST) -> Optional[str]:
    """Dotted base of an attribute access, depth <= 2: `coord` or
    `self._coord`."""
    if isinstance(node, ast.Name):
        return node.id
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)):
        return f"{node.value.id}.{node.attr}"
    return None


class _ScopeEvents:
    __slots__ = ("handles", "stops", "reassigns", "accesses")

    def __init__(self) -> None:
        self.handles: Dict[str, int] = {}        # base -> ctor line
        self.stops: Dict[str, int] = {}          # base -> earliest stop
        self.reassigns: Dict[str, List[int]] = {}
        self.accesses: List[Tuple[str, str, ast.Attribute]] = []


class HandleLifecycleRule(Rule):
    id = "SPK501"
    slug = "handle-lifecycle"
    summary = "native handle used after .stop()/.kill() in the same scope"
    why = ("PR 10's elastic bench segfaulted reading coord.generation "
           "after the finally-stop freed the native gang state; "
           "snapshot before stop, or reassign the handle")

    def run(self, ctx: FileContext) -> Iterator[Finding]:
        idx = ctx.index
        scopes: Dict[int, _ScopeEvents] = {}

        def events(node: ast.AST) -> _ScopeEvents:
            key = id(idx.scope_of.get(id(node)))
            ev = scopes.get(key)
            if ev is None:
                ev = scopes[key] = _ScopeEvents()
            return ev

        for node in idx.assigns:
            value_ctor = (
                isinstance(node.value, ast.Call)
                and (idx.resolve(node.value.func) or ""
                     ).rsplit(".", 1)[-1] in _HANDLE_CTORS)
            ev = events(node)
            for tgt in node.targets:
                base = _base_name(tgt)
                if base is None:
                    continue
                if value_ctor and base not in ev.handles:
                    ev.handles[base] = node.lineno
                ev.reassigns.setdefault(base, []).append(node.lineno)
        for node in idx.calls:
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("stop", "kill")):
                base = _base_name(node.func.value)
                if base is not None:
                    ev = events(node)
                    line = ev.stops.get(base)
                    if line is None or node.lineno < line:
                        ev.stops[base] = node.lineno
        for node in idx.attributes:
            if isinstance(node.ctx, ast.Load):
                base = _base_name(node.value)
                if base is not None:
                    events(node).accesses.append((base, node.attr, node))

        for ev in scopes.values():
            for base, attr, node in ev.accesses:
                if base not in ev.handles or base not in ev.stops:
                    continue
                stop_line = ev.stops[base]
                if stop_line < ev.handles[base]:
                    continue  # stop of a previous incarnation
                if node.lineno <= stop_line or attr in _SAFE_AFTER_STOP:
                    continue
                if any(stop_line < ln <= node.lineno
                       for ln in ev.reassigns.get(base, [])):
                    continue
                yield self.finding(
                    ctx, node,
                    f"`{base}.{attr}` read after `{base}.stop()/.kill()` "
                    f"(line {stop_line}) with no reassignment — native "
                    f"handle state is freed on stop (the PR 10 "
                    f"stopped-GangCoordinator segfault); snapshot "
                    f"before stopping, or annotate a documented "
                    f"post-stop-safe property with "
                    f"`# lint-obs: ok (<why>)`")
