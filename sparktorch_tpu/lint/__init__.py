"""sparklint — the repo's AST-based static-analysis pass.

Every rule encodes a bug class this codebase has actually shipped (see
each rule's ``why``); the analyzer replaced the Makefile's grep
stanzas as the tier-1 ``make lint`` prerequisite. CLI::

    python -m sparktorch_tpu.lint [paths] [--json] [--rule ID ...]

Suppression is per-line and shares the historical annotation the greps
established: ``# lint-obs: ok (<why>)`` on the finding's line (or a
pure-comment line directly above it).
"""

from sparktorch_tpu.lint.core import (  # noqa: F401
    FileContext,
    Finding,
    ModuleIndex,
    Rule,
    lint_file,
    run_lint,
)
from sparktorch_tpu.lint.rules_jax import (
    CollectiveContextRule,
    RetraceHazardRule,
)
from sparktorch_tpu.lint.rules_lifecycle import HandleLifecycleRule
from sparktorch_tpu.lint.rules_locks import LockHoldRule
from sparktorch_tpu.lint.rules_obs import (
    AsyncFetchRule,
    BareSpanRule,
    EventKindCollisionRule,
    JsonDumpRule,
    ObsPrintRule,
    ProfilerApiRule,
    SpanContextMintRule,
    UrllibScrapeRule,
)
from sparktorch_tpu.lint.rules_timing import TimingLedgerRule

#: Registry, ordered by rule ID. Adding a rule = subclass
#: :class:`~sparktorch_tpu.lint.core.Rule`, set id/slug/summary/why,
#: implement run(), append here, and give it a true-positive +
#: true-negative fixture pair in tests/fixtures/lint/.
ALL_RULES = (
    ObsPrintRule(),
    BareSpanRule(),
    JsonDumpRule(),
    UrllibScrapeRule(),
    SpanContextMintRule(),
    EventKindCollisionRule(),
    ProfilerApiRule(),
    AsyncFetchRule(),
    TimingLedgerRule(),
    LockHoldRule(),
    RetraceHazardRule(),
    CollectiveContextRule(),
    HandleLifecycleRule(),
)


def rules_by_selector(selectors):
    """Resolve ``--rule`` selectors (rule IDs or slugs, case-
    insensitive) against the registry; raises KeyError naming the
    unknown selector."""
    if not selectors:
        return ALL_RULES
    by_key = {}
    for r in ALL_RULES:
        by_key[r.id.lower()] = r
        by_key[r.slug.lower()] = r
    picked = []
    for sel in selectors:
        rule = by_key.get(sel.lower())
        if rule is None:
            raise KeyError(sel)
        if rule not in picked:
            picked.append(rule)
    return tuple(picked)
