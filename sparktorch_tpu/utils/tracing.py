"""Profiling/tracing hooks (XLA profiler), adapted over the telemetry bus.

The reference ships no profiler hooks at all (SURVEY §5 "Tracing:
none"). Here: a trace context for whole runs and per-step annotations
that show up in the TPU trace viewer, attached at the step loop — the
hook point the survey names (the equivalent of ``distributed.py:141``).

Both hooks are thin adapters over :mod:`sparktorch_tpu.obs`: a
profiled run records a ``tracing.profile`` span (so the trace capture
cost itself is attributed) and step annotations bump a counter — the
existing call-site contract is unchanged.
"""

from __future__ import annotations

import contextlib
from typing import Iterator, Optional

import jax

from sparktorch_tpu.obs import get_telemetry


@contextlib.contextmanager
def profile_run(log_dir: Optional[str], telemetry=None) -> Iterator[None]:
    """Capture an XLA profiler trace for the enclosed block when
    ``log_dir`` is set; no-op otherwise. View with TensorBoard or
    xprof."""
    if not log_dir:
        yield
        return
    import time

    tele = telemetry or get_telemetry()
    tele.counter("tracing.profile_runs")
    # Deliberately NOT a span: a span here would sit on the thread-
    # local stack for the whole run and re-path every trainer span
    # underneath it — metric names must not depend on whether
    # profiling happens to be on. A plain histogram attributes the
    # capture's wall cost instead.
    t0 = time.perf_counter()
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        # log_dir is NOT a label: label values must stay simple tokens
        # (the name{k=v,...} flat-key spelling reserves ',' and '='),
        # and a filesystem path can contain both. The trace location
        # travels on the event instead.
        tele.observe("tracing.profile_s", time.perf_counter() - t0)
        tele.event("profile_trace", log_dir=log_dir)


def step_annotation(step: int, telemetry=None):
    """Per-step trace annotation; shows step boundaries in the trace
    viewer. Also counts dispatched steps on the bus (one cheap counter
    bump — safe on the hot path)."""
    (telemetry or get_telemetry()).counter("tracing.annotated_steps")
    return jax.profiler.StepTraceAnnotation("train_step", step_num=step)
