"""Profiling/tracing hooks (XLA profiler).

The reference ships no profiler hooks at all (SURVEY §5 "Tracing:
none"). Here: a trace context for whole runs and per-step annotations
that show up in the TPU trace viewer, attached at the step loop — the
hook point the survey names (the equivalent of ``distributed.py:141``).
"""

from __future__ import annotations

import contextlib
from typing import Iterator, Optional

import jax


@contextlib.contextmanager
def profile_run(log_dir: Optional[str]) -> Iterator[None]:
    """Capture an XLA profiler trace for the enclosed block when
    ``log_dir`` is set; no-op otherwise. View with TensorBoard or
    xprof."""
    if not log_dir:
        yield
        return
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def step_annotation(step: int):
    """Per-step trace annotation; shows step boundaries in the trace
    viewer."""
    return jax.profiler.StepTraceAnnotation("train_step", step_num=step)
