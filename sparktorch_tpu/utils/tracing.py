"""Profiling/tracing hooks (XLA profiler), adapted over the telemetry bus.

The reference ships no profiler hooks at all (SURVEY §5 "Tracing:
none"). Here: a trace context for whole runs and per-step annotations
that show up in the TPU trace viewer, attached at the step loop — the
hook point the survey names (the equivalent of ``distributed.py:141``).

Both hooks are thin adapters over :mod:`sparktorch_tpu.obs`: a
profiled run records a ``tracing.profile`` span (so the trace capture
cost itself is attributed) and step annotations bump a counter — the
existing call-site contract is unchanged.
"""

from __future__ import annotations

import contextlib
from typing import Iterator, Optional

import jax

from sparktorch_tpu.obs import get_telemetry


def trace_viewer_url(log_dir: str, host: str = "localhost",
                     port: int = 6006) -> str:
    """Ready-to-open TensorBoard/xprof deep link for a captured trace.

    The profile plugin lists runs by the path fragment under the
    logdir, so the URL pins the run to the trace just written; serving
    it is one command (``tensorboard --logdir <dir>`` or
    ``xprof --logdir <dir>``), which rides alongside on the event as
    ``view_cmd``. A regression in a JSONL stream or a ``/telemetry``
    scrape then links straight to its trace instead of a bare
    directory name (ROADMAP: trace-viewer deep links)."""
    import os
    import urllib.parse

    run = os.path.basename(os.path.normpath(log_dir)) or "."
    return (f"http://{host}:{port}/#profile"
            f"&run={urllib.parse.quote(run, safe='')}")


@contextlib.contextmanager
def profile_run(log_dir: Optional[str], telemetry=None,
                analyze: bool = True) -> Iterator[dict]:
    """Capture an XLA profiler trace for the enclosed block when
    ``log_dir`` is set; no-op otherwise. View with TensorBoard or
    xprof.

    At stop time the capture is ALSO machine-read (``analyze=True``):
    :func:`sparktorch_tpu.obs.xprof.analyze_and_publish` slices the
    Chrome trace by the per-step annotations, attributes collective vs
    compute time, and publishes ``xprof.*`` metrics onto the bus — so
    the trace becomes queryable (``/metrics``, JSONL dumps) instead of
    TensorBoard-only. Yields a handle dict whose ``"analysis"`` key
    holds the :class:`TraceAnalysis` after exit (None when profiling
    is off, analysis is disabled, or the runtime emitted no trace)."""
    handle: dict = {"analysis": None}
    if not log_dir:
        yield handle
        return
    import time

    tele = telemetry or get_telemetry()
    tele.counter("tracing.profile_runs")
    # Baseline for the truncation detector: the delta of this counter
    # across the capture is how many step annotations the trace SHOULD
    # contain; fewer markers found means the profiler's event buffer
    # overflowed and dropped them (silent under-reporting otherwise).
    steps_before = tele.counter_value("tracing.annotated_steps")
    # Deliberately NOT a span: a span here would sit on the thread-
    # local stack for the whole run and re-path every trainer span
    # underneath it — metric names must not depend on whether
    # profiling happens to be on. A plain histogram attributes the
    # capture's wall cost instead.
    t0 = time.perf_counter()
    jax.profiler.start_trace(log_dir)
    try:
        yield handle
    finally:
        jax.profiler.stop_trace()
        # log_dir is NOT a label: label values must stay simple tokens
        # (the name{k=v,...} flat-key spelling reserves ',' and '='),
        # and a filesystem path can contain both. The trace location
        # travels on the event instead.
        tele.observe("tracing.profile_s", time.perf_counter() - t0)
        url = trace_viewer_url(log_dir)
        # The URL ALSO lands in the snapshot's info section, so a
        # /telemetry scrape (param server or gang exporter) links
        # straight to the latest trace, not just the JSONL stream.
        tele.info("tracing.trace_url", url)
        tele.event("profile_trace", log_dir=log_dir, trace_url=url,
                   view_cmd=f"tensorboard --logdir {log_dir}")
        if analyze:
            # Failure-safe by contract (a missing/torn capture logs
            # and bumps xprof.analyze_failures, never raises).
            from sparktorch_tpu.obs.xprof import analyze_and_publish

            expected = int(
                tele.counter_value("tracing.annotated_steps") - steps_before
            )
            handle["analysis"] = analyze_and_publish(
                log_dir, telemetry=tele,
                expected_steps=expected if expected > 0 else None,
            )


def step_annotation(step: int, telemetry=None):
    """Per-step trace annotation; shows step boundaries in the trace
    viewer. Also counts dispatched steps on the bus (one cheap counter
    bump — safe on the hot path)."""
    (telemetry or get_telemetry()).counter("tracing.annotated_steps")
    return jax.profiler.StepTraceAnnotation("train_step", step_num=step)
