"""Concurrency primitives for the parameter service.

Parity target: ``sparktorch/rw_lock.py:11-67`` — a monitor-based
writer-priority RW lock guarding the hogwild server's model. The
reference effectively degrades it to a mutex because both the read
route and the update route take the write lock (``server.py:95-99,
128-145``).

TPU-native redesign: readers never block at all. Parameters live as an
immutable pytree snapshot behind a version counter; a pull is a
volatile read of the current (version, snapshot) pair and an update
swaps in a new snapshot under a single-writer mutex. This is the
idiomatic accelerator shape: device arrays are immutable, so "read
lock" is just holding a reference.

``RWLock`` itself is still provided (writer-priority, same semantics)
for API parity and for host-side structures that genuinely mutate.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Dict, Iterable, List, Mapping, Optional, Tuple


class RWLock:
    """Writer-priority reader/writer lock (rw_lock.py:11-67 parity)."""

    def __init__(self):
        self._cond = threading.Condition(threading.Lock())
        self._readers = 0
        self._writers = 0
        self._waiting_writers = 0

    def acquire_read(self) -> None:
        with self._cond:
            while self._writers > 0 or self._waiting_writers > 0:
                self._cond.wait()
            self._readers += 1

    def acquire_write(self) -> None:
        with self._cond:
            self._waiting_writers += 1
            try:
                while self._readers > 0 or self._writers > 0:
                    self._cond.wait()
            finally:
                self._waiting_writers -= 1
            self._writers += 1

    def release_read(self) -> None:
        with self._cond:
            self._readers -= 1
            if self._readers == 0:
                self._cond.notify_all()

    def release_write(self) -> None:
        with self._cond:
            self._writers -= 1
            self._cond.notify_all()

    # The reference exposes a single release() that infers which side
    # to release (rw_lock.py:48-67); keep it for drop-in use.
    def release(self) -> None:
        with self._cond:
            if self._writers > 0:
                self._writers -= 1
            elif self._readers > 0:
                self._readers -= 1
            self._cond.notify_all()


class VersionedSlot:
    """Lock-free-read, single-writer versioned value holder.

    The parameter server keeps its canonical params here: ``read()``
    never blocks (immutable snapshot semantics), ``swap()`` serializes
    writers. Version numbers let pull clients skip redundant transfers
    (the reference re-ships the full state_dict every iteration,
    ``hogwild.py:103`` — the central pathology §3.2 flags).
    """

    def __init__(self, value: Any = None):
        self._write_lock = threading.Lock()
        # Single attribute holding the (version, value) pair: Python
        # reference assignment is atomic, so readers can never observe
        # a torn (new_version, old_value) combination.
        self._snapshot: Tuple[int, Any] = (0, value)

    def read(self) -> Tuple[int, Any]:
        return self._snapshot

    def read_if_newer(self, have_version: int) -> Optional[Tuple[int, Any]]:
        version, value = self._snapshot
        if version > have_version:
            return version, value
        return None

    @property
    def version(self) -> int:
        return self._snapshot[0]

    def swap(self, new_value: Any) -> int:
        with self._write_lock:
            version = self._snapshot[0] + 1
            self._snapshot = (version, new_value)
            return version

    def update(self, fn) -> Tuple[int, Any]:
        """Apply ``fn(old) -> new`` atomically w.r.t. other writers."""
        with self._write_lock:
            version, value = self._snapshot
            self._snapshot = (version + 1, fn(value))
            return self._snapshot


# ---------------------------------------------------------------------------
# Per-leaf versioning (the delta-pull substrate)
# ---------------------------------------------------------------------------

Path = Tuple[str, ...]


def _flatten_value(value: Any) -> Dict[Path, Any]:
    """``{path: leaf}`` from a nested tree (or a bare leaf at ())."""
    flat: Dict[Path, Any] = {}

    def walk(node: Any, prefix: Path) -> None:
        if isinstance(node, Mapping):
            for k in node:
                walk(node[k], prefix + (str(k),))
        else:
            flat[prefix] = node

    walk(value, ())
    return flat


def _unflatten(leaves: Mapping[Path, Any]) -> Any:
    """Nested dict from ``{path: leaf}`` (local twin of
    ``net.wire.unflatten_tree``, kept here so utils/ stays import-free
    of the wire layer)."""
    if len(leaves) == 1 and () in leaves:
        return leaves[()]
    tree: Dict[str, Any] = {}
    for path, value in leaves.items():
        node = tree
        for key in path[:-1]:
            node = node.setdefault(key, {})
        node[path[-1]] = value
    return tree


class TreeVersionedSlot(VersionedSlot):
    """A :class:`VersionedSlot` whose value is a tensor TREE with a
    version tag per LEAF beside the global version.

    This is the server half of delta pulls: ``swap_leaves`` installs
    new values for a subset of paths and stamps exactly those leaves
    with the new global version, so ``read_delta(have)`` answers
    "every leaf that advanced past ``have``" by comparing integers,
    never by diffing tensors. A whole-tree ``swap`` keeps working
    (every leaf re-stamped — the conservative answer).

    ``epoch`` is a random nonce minted at construction and carried on
    every delta reply: a RESTARTED server (fresh slot, version counter
    reset to 0) is detected by epoch mismatch, not by version
    arithmetic — without it, a client holding version N would read the
    fresh server's ``0 <= N`` as "nothing newer" forever and silently
    train on stale weights.

    Reads stay lock-free: the (version, leaves, leaf_versions) triple
    lives in ONE attribute assigned atomically, so a reader can never
    observe new values with old version tags.
    """

    def __init__(self, leaves: Optional[Mapping[Path, Any]] = None,
                 epoch: Optional[int] = None):
        super().__init__(None)
        self.epoch = (int(epoch) if epoch is not None
                      else int.from_bytes(os.urandom(8), "little") >> 1)
        flat: Dict[Path, Any] = dict(leaves or {})
        vers: Dict[Path, int] = {p: 0 for p in flat}
        # (version, {path: leaf}, {path: leaf_version}) — one atomic ref.
        self._delta: Tuple[int, Dict[Path, Any], Dict[Path, int]] = (
            0, flat, vers
        )
        self._snapshot = (0, _unflatten(flat) if flat else {})

    # -- reads (lock-free) -------------------------------------------------

    def read_leaves(self) -> Tuple[int, Dict[Path, Any], Dict[Path, int]]:
        """``(version, {path: leaf}, {path: leaf_version})`` — one
        coherent snapshot."""
        return self._delta

    def read_delta(
        self, have_version: int
    ) -> Optional[Tuple[int, List[Tuple[Path, Any, int]]]]:
        """``(version, [(path, leaf, leaf_version), ...])`` for every
        leaf whose version advanced past ``have_version``; None when
        the client is up to date (the 304 answer)."""
        version, flat, vers = self._delta
        if version <= have_version:
            return None
        return version, [
            (p, flat[p], vers[p]) for p in flat if vers[p] > have_version
        ]

    @property
    def paths(self) -> List[Path]:
        return list(self._delta[1])

    # -- writes (single-writer) --------------------------------------------

    def _commit(self, flat: Dict[Path, Any], vers: Dict[Path, int],
                version: int) -> int:
        # Order matters for the lock-free readers of the LEGACY
        # surface: the nested snapshot is derived first, then both
        # attributes are swapped — each is individually coherent.
        self._delta = (version, flat, vers)
        self._snapshot = (version, _unflatten(flat) if flat else {})
        return version

    def swap_leaves(self, updates: Mapping[Path, Any]) -> int:
        """Install new values for ``updates``' paths; exactly those
        leaves (new paths included) get the bumped global version."""
        with self._write_lock:
            version, flat, vers = self._delta
            version += 1
            flat = dict(flat)
            vers = dict(vers)
            for path, value in updates.items():
                flat[tuple(path)] = value
                vers[tuple(path)] = version
            return self._commit(flat, vers, version)

    def remove_leaves(self, paths: Iterable[Path]) -> Dict[Path, Any]:
        """Drop leaves (a shard draining them to a new owner). Bumps
        the global version so whole-tree pullers refresh; removed
        paths simply stop appearing in deltas."""
        with self._write_lock:
            version, flat, vers = self._delta
            flat = dict(flat)
            vers = dict(vers)
            removed: Dict[Path, Any] = {}
            for path in list(paths):
                path = tuple(path)
                if path in flat:
                    removed[path] = flat.pop(path)
                    vers.pop(path, None)
            if removed:
                self._commit(flat, vers, version + 1)
            return removed

    def swap(self, new_value: Any) -> int:
        """Whole-tree replacement: every leaf of ``new_value`` is
        re-stamped with the new version (the legacy single-version
        contract, kept so a TreeVersionedSlot drops in anywhere a
        VersionedSlot did)."""
        flat = _flatten_value(new_value)
        with self._write_lock:
            version = self._delta[0] + 1
            vers = {p: version for p in flat}
            return self._commit(flat, vers, version)

    def update(self, fn) -> Tuple[int, Any]:
        """Atomic ``fn(old_tree) -> new_tree`` (the inherited
        VersionedSlot contract). Overridden because the base version
        writes only ``_snapshot`` — it would silently desync the
        per-leaf ``_delta`` state the delta wire serves from. Every
        leaf of the result is re-stamped (the conservative answer, as
        with :meth:`swap`)."""
        with self._write_lock:
            _version, tree = self._snapshot
            flat = _flatten_value(fn(tree))
            version = self._delta[0] + 1
            self._commit(flat, {p: version for p in flat}, version)
            return self._snapshot
