"""Concurrency primitives for the parameter service.

Parity target: ``sparktorch/rw_lock.py:11-67`` — a monitor-based
writer-priority RW lock guarding the hogwild server's model. The
reference effectively degrades it to a mutex because both the read
route and the update route take the write lock (``server.py:95-99,
128-145``).

TPU-native redesign: readers never block at all. Parameters live as an
immutable pytree snapshot behind a version counter; a pull is a
volatile read of the current (version, snapshot) pair and an update
swaps in a new snapshot under a single-writer mutex. This is the
idiomatic accelerator shape: device arrays are immutable, so "read
lock" is just holding a reference.

``RWLock`` itself is still provided (writer-priority, same semantics)
for API parity and for host-side structures that genuinely mutate.
"""

from __future__ import annotations

import threading
from typing import Any, Optional, Tuple


class RWLock:
    """Writer-priority reader/writer lock (rw_lock.py:11-67 parity)."""

    def __init__(self):
        self._cond = threading.Condition(threading.Lock())
        self._readers = 0
        self._writers = 0
        self._waiting_writers = 0

    def acquire_read(self) -> None:
        with self._cond:
            while self._writers > 0 or self._waiting_writers > 0:
                self._cond.wait()
            self._readers += 1

    def acquire_write(self) -> None:
        with self._cond:
            self._waiting_writers += 1
            try:
                while self._readers > 0 or self._writers > 0:
                    self._cond.wait()
            finally:
                self._waiting_writers -= 1
            self._writers += 1

    def release_read(self) -> None:
        with self._cond:
            self._readers -= 1
            if self._readers == 0:
                self._cond.notify_all()

    def release_write(self) -> None:
        with self._cond:
            self._writers -= 1
            self._cond.notify_all()

    # The reference exposes a single release() that infers which side
    # to release (rw_lock.py:48-67); keep it for drop-in use.
    def release(self) -> None:
        with self._cond:
            if self._writers > 0:
                self._writers -= 1
            elif self._readers > 0:
                self._readers -= 1
            self._cond.notify_all()


class VersionedSlot:
    """Lock-free-read, single-writer versioned value holder.

    The parameter server keeps its canonical params here: ``read()``
    never blocks (immutable snapshot semantics), ``swap()`` serializes
    writers. Version numbers let pull clients skip redundant transfers
    (the reference re-ships the full state_dict every iteration,
    ``hogwild.py:103`` — the central pathology §3.2 flags).
    """

    def __init__(self, value: Any = None):
        self._write_lock = threading.Lock()
        # Single attribute holding the (version, value) pair: Python
        # reference assignment is atomic, so readers can never observe
        # a torn (new_version, old_value) combination.
        self._snapshot: Tuple[int, Any] = (0, value)

    def read(self) -> Tuple[int, Any]:
        return self._snapshot

    def read_if_newer(self, have_version: int) -> Optional[Tuple[int, Any]]:
        version, value = self._snapshot
        if version > have_version:
            return version, value
        return None

    @property
    def version(self) -> int:
        return self._snapshot[0]

    def swap(self, new_value: Any) -> int:
        with self._write_lock:
            version = self._snapshot[0] + 1
            self._snapshot = (version, new_value)
            return version

    def update(self, fn) -> Tuple[int, Any]:
        """Apply ``fn(old) -> new`` atomically w.r.t. other writers."""
        with self._write_lock:
            version, value = self._snapshot
            self._snapshot = (version + 1, fn(value))
            return self._snapshot
