"""Structured per-step training metrics + roll-ups.

The reference's entire observability story is a ``verbose`` int that
gates raw ``print`` of per-partition losses (``distributed.py:201-204``,
``hogwild.py:133-134``; SURVEY §5 "Metrics: minimal"). This module is
the structured replacement, shaped around the BASELINE north-star
numbers: examples/sec/chip, mean/p50/p99 step time, loss curves.
"""

from __future__ import annotations

import json
import time
from typing import Any, Dict, List

import numpy as np


class MetricsRecorder:
    def __init__(self, n_chips: int = 1):
        self.n_chips = max(1, n_chips)
        self.records: List[Dict[str, Any]] = []
        self._t_start = time.perf_counter()

    def record(self, rec: Dict[str, Any]) -> None:
        self.records.append(rec)

    # -- roll-ups (the BASELINE.md protocol numbers) -----------------------

    def summary(self) -> Dict[str, Any]:
        if not self.records:
            return {"steps": 0}
        times = np.asarray([r["step_time_s"] for r in self.records
                            if r.get("step_time_s")])
        examples = float(sum(r.get("examples", 0.0) for r in self.records))
        wall = time.perf_counter() - self._t_start
        losses = [r["loss"] for r in self.records if r.get("loss") is not None]
        out = {
            "steps": len(self.records),
            "total_examples": examples,
            "wall_time_s": round(wall, 4),
            "examples_per_sec": round(examples / wall, 2) if wall > 0 else None,
            "examples_per_sec_per_chip": round(examples / wall / self.n_chips, 2)
            if wall > 0 else None,
            "first_loss": losses[0] if losses else None,
            "final_loss": losses[-1] if losses else None,
        }
        if times.size:
            out.update(
                step_time_mean_s=round(float(times.mean()), 6),
                step_time_p50_s=round(float(np.percentile(times, 50)), 6),
                step_time_p99_s=round(float(np.percentile(times, 99)), 6),
            )
        return out

    def to_jsonl(self, path: str) -> None:
        with open(path, "w") as f:
            for rec in self.records:
                f.write(json.dumps(rec) + "\n")
            f.write(json.dumps({"summary": self.summary()}) + "\n")
