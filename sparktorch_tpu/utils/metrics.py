"""Structured per-step training metrics + roll-ups.

The reference's entire observability story is a ``verbose`` int that
gates raw ``print`` of per-partition losses (``distributed.py:201-204``,
``hogwild.py:133-134``; SURVEY §5 "Metrics: minimal"). This module is
the structured replacement, shaped around the BASELINE north-star
numbers: examples/sec/chip, mean/p50/p99 step time, loss curves.

Since the telemetry subsystem landed (:mod:`sparktorch_tpu.obs`), the
recorder is a thin adapter over the shared bus: every ``record()``
also bumps the run's counters and step-time histogram, so existing
call sites keep working while the same numbers surface on ``/metrics``
and in the JSONL event stream.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import numpy as np


class MetricsRecorder:
    """Collects per-step record dicts; rolls them up into the
    BASELINE.md protocol numbers.

    ``telemetry`` (optional): a :class:`sparktorch_tpu.obs.Telemetry`
    to mirror into — counters ``<prefix>.steps`` / ``<prefix>.examples``
    and histogram ``<prefix>.step_s`` — so a run's recorder and its
    ``/metrics`` view share one source of truth.
    """

    def __init__(self, n_chips: int = 1, telemetry=None,
                 prefix: str = "train"):
        self.n_chips = max(1, n_chips)
        self.records: List[Dict[str, Any]] = []
        self.telemetry = telemetry
        self.prefix = prefix
        # Per-record wall-clock stamps (perf_counter). Wall time is
        # last-first over THESE, not construction-to-summary: a
        # recorder built before compilation/warmup must not charge
        # that dead time to throughput (the old behavior inflated
        # wall_time_s and deflated examples_per_sec).
        self._stamps: List[float] = []

    def record(self, rec: Dict[str, Any]) -> None:
        self._stamps.append(time.perf_counter())
        self.records.append(rec)
        tele = self.telemetry
        if tele is not None:
            tele.counter(f"{self.prefix}.steps")
            examples = rec.get("examples")
            if examples:
                tele.counter(f"{self.prefix}.examples", float(examples))
            dt = rec.get("step_time_s")
            if dt:
                tele.observe(f"{self.prefix}.step_s", float(dt))
            loss = rec.get("loss")
            if loss is not None and np.isfinite(loss):
                tele.gauge(f"{self.prefix}.loss", float(loss))

    # -- roll-ups (the BASELINE.md protocol numbers) -----------------------

    def _wall_s(self) -> float:
        """Measured span of the recorded steps: last-stamp minus
        first-stamp, plus the first step's own duration (the first
        stamp lands AFTER step 0 completed, so last-first alone would
        exclude it — and would be 0 for a single-record run)."""
        if not self._stamps:
            return 0.0
        wall = self._stamps[-1] - self._stamps[0]
        first_dt = self.records[0].get("step_time_s") or 0.0
        return wall + float(first_dt)

    def summary(self) -> Dict[str, Any]:
        if not self.records:
            return {"steps": 0}
        times = np.asarray([r["step_time_s"] for r in self.records
                            if r.get("step_time_s")])
        examples = float(sum(r.get("examples", 0.0) for r in self.records))
        wall = self._wall_s()
        losses = [r["loss"] for r in self.records if r.get("loss") is not None]
        out = {
            "steps": len(self.records),
            "total_examples": examples,
            "wall_time_s": round(wall, 4),
            "examples_per_sec": round(examples / wall, 2) if wall > 0 else None,
            "examples_per_sec_per_chip": round(examples / wall / self.n_chips, 2)
            if wall > 0 else None,
            "first_loss": losses[0] if losses else None,
            "final_loss": losses[-1] if losses else None,
        }
        if times.size:
            out.update(
                step_time_mean_s=round(float(times.mean()), 6),
                step_time_p50_s=round(float(np.percentile(times, 50)), 6),
                step_time_p99_s=round(float(np.percentile(times, 99)), 6),
            )
        return out

    def to_jsonl(self, path: str, append: bool = False) -> None:
        """Write per-step records + a summary line. Parent directories
        are created; ``append=True`` accumulates across phases instead
        of clobbering earlier records (multi-phase runs: warmup then
        measure, resumed jobs, shuffle rounds)."""
        from sparktorch_tpu.obs.sinks import write_jsonl

        write_jsonl(
            path,
            [*self.records, {"summary": self.summary()}],
            append=append,
        )
