"""Step-indexed checkpoint / resume (orbax-backed).

The reference has NO mid-training checkpointing (SURVEY §5): the only
persistence is the final state_dict wrapped into the fitted model
(``torch_distributed.py:339-348``). This module adds the subsystem at
the hook point the survey identifies (where the reference returns its
state_dict, ``distributed.py:206``): step-indexed snapshots of the
FULL TrainState — params, optimizer state, step counter, rng — with
retention, atomic finalize, and resume.

Sharded-state aware: orbax restores each leaf directly into the
sharding of the abstract target, so a resumed fsdp/tp run never
materializes the full model on one host.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import orbax.checkpoint as ocp

from sparktorch_tpu.obs import goodput as _goodput


# How many orbax restores have run in this process. Read by
# arm_persistent_cache (arming after a restore would re-create the
# crash the disarm exists to prevent) and by tests pinning the
# disarm-really-disarms contract.
_RESTORE_COUNT = 0


def restore_count() -> int:
    """Orbax restores seen by this process (any of the module's
    restore paths). Nonzero means the persistent compilation cache has
    been disarmed for the remainder of the process on CPU."""
    return _RESTORE_COUNT


def _disarm_persistent_cache_after_restore() -> None:
    """Work around a jax-0.4.x CPU crash: an orbax restore anywhere in
    the process, followed by compiling/dispatching collective programs
    THROUGH the armed persistent compilation cache, SIGABRTs in
    dispatch (bisected in tests/conftest.py: restore -> streaming
    trainer's collectives aborts deterministically even on a COLD
    cache dir; the same programs compiled with the cache off are
    fine). Until the runtime is fixed, a restore flips the persistent
    cache OFF for the remainder of the process: everything before the
    first restore still gets cache speed, and resumed runs pay fresh
    compiles instead of a segfault.

    Nulling ``jax_compilation_cache_dir`` alone is NOT a disarm once
    any compile has happened: jax's ``compilation_cache.is_cache_used``
    latches a module-global ``_cache_used`` at the first compile and
    ``_get_cache`` keeps serving the already-initialized cache object
    — the config flip is invisible to both (verified against this
    build; the bisected pair crashed WITH the config-only hook in
    place, leaving the runtime in a half-disabled state: latched-on
    reads against config-gated writes). ``reset_cache()`` drops the
    latch and the cache object, so the next compile re-evaluates the
    (now null) config and runs uncached.

    A softer "reset but keep the dir armed" variant (post-restore
    compiles get a coherent FRESH cache) was tried and REJECTED: the
    checkpoint+train_sync suite still aborts under it — the crash is
    the restore <-> cache-mediated collective interaction itself, not
    stale latch state. Disarm-for-the-rest-of-the-process is the only
    mode the full suite survives."""
    global _RESTORE_COUNT
    _RESTORE_COUNT += 1
    if jax.default_backend() != "cpu":
        return
    try:
        if not jax.config.jax_compilation_cache_dir:
            return
        jax.config.update("jax_compilation_cache_dir", None)
    except AttributeError:  # config knob renamed/absent on this build
        return
    try:
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()
    except Exception:  # noqa: BLE001 - private API; degrade to config-only
        pass


def persistent_cache_armed() -> bool:
    """Whether the jax persistent compilation cache is currently
    armed (a cache dir is configured)."""
    try:
        return bool(jax.config.jax_compilation_cache_dir)
    except AttributeError:
        return False


def arm_persistent_cache(cache_dir: str,
                         min_compile_time_s: float = 0.3) -> bool:
    """Arm the jax persistent compilation cache at ``cache_dir`` —
    the runtime-level antidote to the recompile tax (ROADMAP item 4b):
    every XLA compile past ``min_compile_time_s`` serializes to disk,
    and an identical program compiled later (a fresh jit closure, the
    mesh='auto' winner's second compile, the next process) is a disk
    hit instead of a recompile.

    Refuses (returns False) when a restore already ran in this
    process ON THE CPU BACKEND — arming then would re-create the
    restore↔collective SIGABRT the disarm hook exists to prevent
    (the crash never reproduces off-CPU, so restores there don't
    forfeit the cache). When a cache dir is already configured the
    call defers to it and returns True (first armer wins; the return
    means "a cache is armed", not "YOUR dir is armed"). Mid-process
    arming needs the same ``reset_cache()`` un-latch as the disarm:
    jax latches "no cache" at the first uncached compile."""
    if _RESTORE_COUNT > 0 and jax.default_backend() == "cpu":
        return False
    if persistent_cache_armed():
        return True
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          float(min_compile_time_s))
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except AttributeError:
        # A knob renamed on this build: never leave the cache HALF
        # armed (dir set, thresholds defaulted, latch not reset).
        try:
            jax.config.update("jax_compilation_cache_dir", None)
        except AttributeError:
            pass
        return False
    try:
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()
    except Exception:  # noqa: BLE001 - private API; the latch may bite
        pass
    return True


_ORBAX_TMP_MARKER = ".orbax-checkpoint-tmp"


def latest_step(directory: str) -> Optional[int]:
    """Newest FINALIZED snapshot step in a checkpoint directory, from
    a plain directory scan — no orbax ``CheckpointManager`` is
    instantiated, so the supervisor's restart path (and an estimator
    deciding whether a resume is even possible) can auto-discover
    checkpoints cheaply and safely while another process may still be
    writing.

    A finalized step is a non-empty, all-digits directory name with no
    orbax tmp marker anywhere in it; in-progress or interrupted saves
    (``<step>.orbax-checkpoint-tmp-<ts>``, or a step dir still holding
    tmp items) are skipped, never returned as resumable. Returns None
    when the directory is missing or holds nothing finalized."""
    try:
        names = os.listdir(directory)
    except OSError:
        return None
    steps = []
    for name in names:
        if _ORBAX_TMP_MARKER in name or not name.isdigit():
            continue
        path = os.path.join(directory, name)
        if not os.path.isdir(path):
            continue
        try:
            entries = os.listdir(path)
        except OSError:
            continue
        if not entries or any(_ORBAX_TMP_MARKER in e for e in entries):
            continue
        steps.append(int(name))
    return max(steps) if steps else None


def _is_typed_key(leaf: Any) -> bool:
    dtype = getattr(leaf, "dtype", None)
    return dtype is not None and jax.dtypes.issubdtype(
        dtype, jax.dtypes.prng_key
    )


def _encode_keys(tree: Any) -> Any:
    """Typed PRNG keys -> raw uint32 key data. Orbax cannot serialize
    extended-dtype key arrays (it np.asarray's every leaf, which
    typed keys refuse), so keys cross the checkpoint boundary as the
    integer data jax.random.key_data extracts."""
    return jax.tree.map(
        lambda l: jax.random.key_data(l) if _is_typed_key(l) else l, tree
    )


def _encode_abstract_keys(tree: Any) -> Any:
    """The abstract-pytree mirror of :func:`_encode_keys`: key-dtype
    ShapeDtypeStructs become the shape/dtype of their key data, so
    the restore target matches what save() actually wrote."""
    return jax.tree.map(
        lambda l: jax.eval_shape(jax.random.key_data, l)
        if _is_typed_key(l) else l,
        tree,
    )


def _decode_keys(restored: Any, abstract: Any) -> Any:
    """Re-wrap restored key data wherever the abstract target asked
    for a typed key (default impl — the only one the trainers use)."""
    return jax.tree.map(
        lambda a, r: jax.random.wrap_key_data(r) if _is_typed_key(a) else r,
        abstract, restored,
    )


class CheckpointManager:
    """Thin wrapper over ``ocp.CheckpointManager`` for NamedTuple
    train states (TrainState, PipelineState, ...)."""

    def __init__(self, directory: str, max_to_keep: int = 3,
                 save_interval_steps: int = 1):
        self._dir = os.path.abspath(directory)
        os.makedirs(self._dir, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps,
                enable_async_checkpointing=False,
            ),
        )

    def save(self, step: int, state: Any, force: bool = False) -> bool:
        # The save wall lands in the goodput ledger's ``checkpoint``
        # bucket (ambient: a run without a ledger pays two
        # perf_counter reads). Nested under a step-chunk span it
        # subtracts cleanly — one second of wall, one bucket.
        with _goodput.span("checkpoint", {"op": "save"}):
            saved = self._mgr.save(
                step,
                args=ocp.args.StandardSave(_encode_keys(state._asdict())),
                force=force,
            )
        return bool(saved)

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self) -> list:
        """Retained checkpoint steps (bounded by ``max_to_keep``)."""
        return list(self._mgr.all_steps())

    def restore(self, abstract_state: Any,
                step: Optional[int] = None) -> Any:
        """Restore into the layout described by ``abstract_state``
        (ShapeDtypeStructs with shardings — use ``jax.eval_shape`` +
        the trainer's sharding pytree). Works for any NamedTuple state
        (TrainState, the pipeline trainer's PipelineState, ...)."""
        step = step if step is not None else self._mgr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint in {self._dir}")
        abstract = abstract_state._asdict()
        with _goodput.span("checkpoint", {"op": "restore"}):
            restored = self._mgr.restore(
                step,
                args=ocp.args.StandardRestore(
                    _encode_abstract_keys(abstract)),
            )
        _disarm_persistent_cache_after_restore()
        return type(abstract_state)(**_decode_keys(restored, abstract))

    def wait(self):
        self._mgr.wait_until_finished()

    def close(self):
        self._mgr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def save_model(directory: str, params: Any, model_state: Any = None) -> None:
    """One-shot final-model save (the reference's only persistence
    behavior, done properly: a real checkpoint format instead of a
    dill blob in a string column)."""
    path = os.path.abspath(directory)
    ckptr = ocp.StandardCheckpointer()
    with _goodput.span("checkpoint", {"op": "save_model"}):
        ckptr.save(os.path.join(path, "model"),
                   {"params": params, "model_state": model_state or {}})
        ckptr.wait_until_finished()


def load_model(directory: str, abstract: Optional[Any] = None):
    path = os.path.abspath(directory)
    ckptr = ocp.StandardCheckpointer()
    target = None
    if abstract is not None:
        target = {"params": abstract, "model_state": {}}
    with _goodput.span("checkpoint", {"op": "load_model"}):
        out = ckptr.restore(os.path.join(path, "model"), target)
    _disarm_persistent_cache_after_restore()
    return out["params"], out.get("model_state") or {}
