"""Step-indexed checkpoint / resume (orbax-backed).

The reference has NO mid-training checkpointing (SURVEY §5): the only
persistence is the final state_dict wrapped into the fitted model
(``torch_distributed.py:339-348``). This module adds the subsystem at
the hook point the survey identifies (where the reference returns its
state_dict, ``distributed.py:206``): step-indexed snapshots of the
FULL TrainState — params, optimizer state, step counter, rng — with
retention, atomic finalize, and resume.

Sharded-state aware: orbax restores each leaf directly into the
sharding of the abstract target, so a resumed fsdp/tp run never
materializes the full model on one host.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import orbax.checkpoint as ocp

from sparktorch_tpu.obs import goodput as _goodput


def _disarm_persistent_cache_after_restore() -> None:
    """Work around a jax-0.4.x CPU crash: executing a persistent-
    compilation-cache DESERIALIZED executable with collectives after an
    orbax restore has run in the same process segfaults in pxla
    ``__call__`` (reproduced deterministically: train+save, then
    resume — the resumed step's cache-hit executable crashes; a fresh
    compile of the identical program is fine). Until the runtime is
    fixed, a restore flips the persistent cache OFF for the remainder
    of the process: everything before the first restore still gets
    cache speed, and resumed runs pay one fresh compile instead of a
    segfault."""
    if jax.default_backend() != "cpu":
        return
    try:
        if jax.config.jax_compilation_cache_dir:
            jax.config.update("jax_compilation_cache_dir", None)
    except AttributeError:  # config knob renamed/absent on this build
        pass


_ORBAX_TMP_MARKER = ".orbax-checkpoint-tmp"


def latest_step(directory: str) -> Optional[int]:
    """Newest FINALIZED snapshot step in a checkpoint directory, from
    a plain directory scan — no orbax ``CheckpointManager`` is
    instantiated, so the supervisor's restart path (and an estimator
    deciding whether a resume is even possible) can auto-discover
    checkpoints cheaply and safely while another process may still be
    writing.

    A finalized step is a non-empty, all-digits directory name with no
    orbax tmp marker anywhere in it; in-progress or interrupted saves
    (``<step>.orbax-checkpoint-tmp-<ts>``, or a step dir still holding
    tmp items) are skipped, never returned as resumable. Returns None
    when the directory is missing or holds nothing finalized."""
    try:
        names = os.listdir(directory)
    except OSError:
        return None
    steps = []
    for name in names:
        if _ORBAX_TMP_MARKER in name or not name.isdigit():
            continue
        path = os.path.join(directory, name)
        if not os.path.isdir(path):
            continue
        try:
            entries = os.listdir(path)
        except OSError:
            continue
        if not entries or any(_ORBAX_TMP_MARKER in e for e in entries):
            continue
        steps.append(int(name))
    return max(steps) if steps else None


def _is_typed_key(leaf: Any) -> bool:
    dtype = getattr(leaf, "dtype", None)
    return dtype is not None and jax.dtypes.issubdtype(
        dtype, jax.dtypes.prng_key
    )


def _encode_keys(tree: Any) -> Any:
    """Typed PRNG keys -> raw uint32 key data. Orbax cannot serialize
    extended-dtype key arrays (it np.asarray's every leaf, which
    typed keys refuse), so keys cross the checkpoint boundary as the
    integer data jax.random.key_data extracts."""
    return jax.tree.map(
        lambda l: jax.random.key_data(l) if _is_typed_key(l) else l, tree
    )


def _encode_abstract_keys(tree: Any) -> Any:
    """The abstract-pytree mirror of :func:`_encode_keys`: key-dtype
    ShapeDtypeStructs become the shape/dtype of their key data, so
    the restore target matches what save() actually wrote."""
    return jax.tree.map(
        lambda l: jax.eval_shape(jax.random.key_data, l)
        if _is_typed_key(l) else l,
        tree,
    )


def _decode_keys(restored: Any, abstract: Any) -> Any:
    """Re-wrap restored key data wherever the abstract target asked
    for a typed key (default impl — the only one the trainers use)."""
    return jax.tree.map(
        lambda a, r: jax.random.wrap_key_data(r) if _is_typed_key(a) else r,
        abstract, restored,
    )


class CheckpointManager:
    """Thin wrapper over ``ocp.CheckpointManager`` for NamedTuple
    train states (TrainState, PipelineState, ...)."""

    def __init__(self, directory: str, max_to_keep: int = 3,
                 save_interval_steps: int = 1):
        self._dir = os.path.abspath(directory)
        os.makedirs(self._dir, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps,
                enable_async_checkpointing=False,
            ),
        )

    def save(self, step: int, state: Any, force: bool = False) -> bool:
        # The save wall lands in the goodput ledger's ``checkpoint``
        # bucket (ambient: a run without a ledger pays two
        # perf_counter reads). Nested under a step-chunk span it
        # subtracts cleanly — one second of wall, one bucket.
        with _goodput.span("checkpoint", {"op": "save"}):
            saved = self._mgr.save(
                step,
                args=ocp.args.StandardSave(_encode_keys(state._asdict())),
                force=force,
            )
        return bool(saved)

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self) -> list:
        """Retained checkpoint steps (bounded by ``max_to_keep``)."""
        return list(self._mgr.all_steps())

    def restore(self, abstract_state: Any,
                step: Optional[int] = None) -> Any:
        """Restore into the layout described by ``abstract_state``
        (ShapeDtypeStructs with shardings — use ``jax.eval_shape`` +
        the trainer's sharding pytree). Works for any NamedTuple state
        (TrainState, the pipeline trainer's PipelineState, ...)."""
        step = step if step is not None else self._mgr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint in {self._dir}")
        abstract = abstract_state._asdict()
        with _goodput.span("checkpoint", {"op": "restore"}):
            restored = self._mgr.restore(
                step,
                args=ocp.args.StandardRestore(
                    _encode_abstract_keys(abstract)),
            )
        _disarm_persistent_cache_after_restore()
        return type(abstract_state)(**_decode_keys(restored, abstract))

    def wait(self):
        self._mgr.wait_until_finished()

    def close(self):
        self._mgr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def save_model(directory: str, params: Any, model_state: Any = None) -> None:
    """One-shot final-model save (the reference's only persistence
    behavior, done properly: a real checkpoint format instead of a
    dill blob in a string column)."""
    path = os.path.abspath(directory)
    ckptr = ocp.StandardCheckpointer()
    with _goodput.span("checkpoint", {"op": "save_model"}):
        ckptr.save(os.path.join(path, "model"),
                   {"params": params, "model_state": model_state or {}})
        ckptr.wait_until_finished()


def load_model(directory: str, abstract: Optional[Any] = None):
    path = os.path.abspath(directory)
    ckptr = ocp.StandardCheckpointer()
    target = None
    if abstract is not None:
        target = {"params": abstract, "model_state": {}}
    with _goodput.span("checkpoint", {"op": "load_model"}):
        out = ckptr.restore(os.path.join(path, "model"), target)
    _disarm_persistent_cache_after_restore()
    return out["params"], out.get("model_state") or {}
