"""Step-indexed checkpoint / resume (orbax-backed).

The reference has NO mid-training checkpointing (SURVEY §5): the only
persistence is the final state_dict wrapped into the fitted model
(``torch_distributed.py:339-348``). This module adds the subsystem at
the hook point the survey identifies (where the reference returns its
state_dict, ``distributed.py:206``): step-indexed snapshots of the
FULL TrainState — params, optimizer state, step counter, rng — with
retention, atomic finalize, and resume.

Sharded-state aware: orbax restores each leaf directly into the
sharding of the abstract target, so a resumed fsdp/tp run never
materializes the full model on one host.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import orbax.checkpoint as ocp



class CheckpointManager:
    """Thin wrapper over ``ocp.CheckpointManager`` for NamedTuple
    train states (TrainState, PipelineState, ...)."""

    def __init__(self, directory: str, max_to_keep: int = 3,
                 save_interval_steps: int = 1):
        self._dir = os.path.abspath(directory)
        os.makedirs(self._dir, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps,
                enable_async_checkpointing=False,
            ),
        )

    def save(self, step: int, state: Any, force: bool = False) -> bool:
        saved = self._mgr.save(
            step, args=ocp.args.StandardSave(state._asdict()), force=force
        )
        return bool(saved)

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self) -> list:
        """Retained checkpoint steps (bounded by ``max_to_keep``)."""
        return list(self._mgr.all_steps())

    def restore(self, abstract_state: Any,
                step: Optional[int] = None) -> Any:
        """Restore into the layout described by ``abstract_state``
        (ShapeDtypeStructs with shardings — use ``jax.eval_shape`` +
        the trainer's sharding pytree). Works for any NamedTuple state
        (TrainState, the pipeline trainer's PipelineState, ...)."""
        step = step if step is not None else self._mgr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint in {self._dir}")
        restored = self._mgr.restore(
            step, args=ocp.args.StandardRestore(abstract_state._asdict())
        )
        return type(abstract_state)(**restored)

    def wait(self):
        self._mgr.wait_until_finished()

    def close(self):
        self._mgr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def save_model(directory: str, params: Any, model_state: Any = None) -> None:
    """One-shot final-model save (the reference's only persistence
    behavior, done properly: a real checkpoint format instead of a
    dill blob in a string column)."""
    path = os.path.abspath(directory)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(os.path.join(path, "model"),
               {"params": params, "model_state": model_state or {}})
    ckptr.wait_until_finished()


def load_model(directory: str, abstract: Optional[Any] = None):
    path = os.path.abspath(directory)
    ckptr = ocp.StandardCheckpointer()
    target = None
    if abstract is not None:
        target = {"params": abstract, "model_state": {}}
    out = ckptr.restore(os.path.join(path, "model"), target)
    return out["params"], out.get("model_state") or {}
