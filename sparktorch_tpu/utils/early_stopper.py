"""Patience-based early stopping.

Parity: ``sparktorch/early_stopper.py:8-56`` — best-metric tracker with
min/max mode, abs/pct ("rel") delta, NaN -> immediate stop, and the
patience-0 degenerate mode that never stops. Used per-driver here: in
SPMD the jitted step returns a *globally reduced* loss replicated on
every host, so each host's stopper reaches the identical decision and
no separate stop-flag all_reduce is needed (the reference needed two
extra collectives per step for this, ``distributed.py:186-197``).
"""

from __future__ import annotations

import math
from typing import Optional


class EarlyStopping:
    def __init__(
        self,
        mode: str = "min",
        min_delta: float = 0.0,
        patience: int = 10,
        percentage: bool = False,
    ):
        if mode not in ("min", "max"):
            raise ValueError(f"mode {mode!r} is unknown")
        self.mode = mode
        self.min_delta = min_delta
        self.patience = patience
        self.percentage = percentage
        self.best: Optional[float] = None
        self.num_bad_epochs = 0

    def step(self, metric: float) -> bool:
        """Returns True when training should stop."""
        metric = float(metric)
        if self.patience == 0:
            # Degenerate mode: track nothing, never stop
            # (early_stopper.py:19-21).
            return False
        if self.best is None:
            self.best = metric
            return False
        if math.isnan(metric):
            return True  # early_stopper.py:28-29
        if self._is_better(metric):
            self.num_bad_epochs = 0
            self.best = metric
        else:
            self.num_bad_epochs += 1
        return self.num_bad_epochs >= self.patience

    def _is_better(self, metric: float) -> bool:
        # early_stopper.py:42-56
        if not self.percentage:
            if self.mode == "min":
                return metric < self.best - self.min_delta
            return metric > self.best + self.min_delta
        # SIGNED best (early_stopper.py:51-56 uses `best * min_delta
        # / 100` with no abs): for negative best the threshold moves
        # toward zero, and the fused jax stopper matches exactly.
        delta = self.best * self.min_delta / 100.0
        if self.mode == "min":
            return metric < self.best - delta
        return metric > self.best + delta

    def reset(self) -> None:
        self.best = None
        self.num_bad_epochs = 0
