"""Loss library.

The reference ships the loss as an arbitrary pickled ``torch.nn``
criterion inside the TorchObj envelope (``util.py:30-32``) and works
around integer-label dtype mismatches with a try/except retry that
re-runs the forward with ``.long()`` labels
(``distributed.py:153-158``, ``hogwild.py:108-113``).

Here losses are pure functions ``(preds, targets) -> per-example loss``
and the dtype question is settled *statically* at trace time: each loss
declares what target dtype it needs and promotes once, so there is no
runtime retry (which would be untraceable under ``jit`` anyway).

Per-example (unreduced) losses are returned so the training step can
apply example weights — the mechanism that replaces the reference's
phantom-rank / empty-partition protocol (``distributed.py:46-63``):
an empty shard contributes weight-zero examples instead of a separate
zero-gradient all_reduce participant.
"""

from __future__ import annotations

from typing import Callable, Union

import jax
import jax.numpy as jnp

LossFn = Callable[[jax.Array, jax.Array], jax.Array]


def _flatten_per_example(x: jax.Array) -> jax.Array:
    """Mean over all non-batch dims -> shape (batch,)."""
    if x.ndim <= 1:
        return x
    return jnp.mean(x.reshape(x.shape[0], -1), axis=-1)


def _align(preds: jax.Array, targets: jax.Array):
    """Rank-align regression preds/targets so (batch,) vs (batch, 1)
    never broadcasts into a (batch, batch) matrix. The reference's
    analog failure is the dtype/shape RuntimeError it retries around
    (distributed.py:153-158); here alignment is static."""
    targets = targets.astype(preds.dtype)
    if targets.ndim < preds.ndim:
        targets = targets.reshape(targets.shape + (1,) * (preds.ndim - targets.ndim))
    elif preds.ndim < targets.ndim:
        preds = preds.reshape(preds.shape + (1,) * (targets.ndim - preds.ndim))
    return preds, targets


def mse_loss(preds: jax.Array, targets: jax.Array) -> jax.Array:
    preds, targets = _align(preds, targets)
    return _flatten_per_example((preds - targets) ** 2)


def l1_loss(preds: jax.Array, targets: jax.Array) -> jax.Array:
    preds, targets = _align(preds, targets)
    return _flatten_per_example(jnp.abs(preds - targets))


def huber_loss(preds: jax.Array, targets: jax.Array, delta: float = 1.0) -> jax.Array:
    preds, targets = _align(preds, targets)
    err = jnp.abs(preds - targets)
    quad = jnp.minimum(err, delta)
    lin = err - quad
    return _flatten_per_example(0.5 * quad**2 + delta * lin)


def cross_entropy_loss(preds: jax.Array, targets: jax.Array) -> jax.Array:
    """Softmax cross entropy over the last axis of ``preds``.

    Integer targets are class indices (the reference's ``.long()``
    retry path); float targets of matching shape are soft labels.
    """
    logz = jax.nn.logsumexp(preds, axis=-1, keepdims=True)
    logp = preds - logz
    if jnp.issubdtype(targets.dtype, jnp.floating) and targets.shape == preds.shape:
        return -jnp.sum(targets * logp, axis=-1).reshape(preds.shape[0], -1).mean(-1)
    labels = targets.astype(jnp.int32)
    if labels.ndim == preds.ndim:  # (batch, 1) style
        labels = labels.reshape(labels.shape[:-1])
    picked = jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    return -picked.reshape(preds.shape[0], -1).mean(-1)


def fused_cross_entropy_loss(preds: jax.Array, targets: jax.Array) -> jax.Array:
    """The Pallas streaming-CE kernel (ops/fused_ce.py): identical math
    to :func:`cross_entropy_loss` for integer labels, but the softmax
    never materializes in HBM in either direction. Lazy import keeps
    Pallas out of the import path for non-LM users."""
    from sparktorch_tpu.ops.fused_ce import fused_cross_entropy_loss as _fce

    return _fce(preds, targets)


def cross_entropy_auto(preds: jax.Array, targets: jax.Array) -> jax.Array:
    """``cross_entropy`` registry entry. LM-shaped integer-label logits
    (batch, seq, vocab) dispatch to the fused Pallas kernel — the
    workload it was built for (CausalLM training) — at trace time;
    everything else takes the dense path.

    GSPMD-aware fallback: under a GSPMD mesh on a non-TPU backend the
    Pallas kernel runs in INTERPRET mode and lowers to a while loop
    the partitioner can only handle by all-gathering the logits into
    every shard — a spurious all-gather that pollutes collective
    profiles and, now that the goodput ledger attributes exposed comm,
    the ``exposed_comm`` bucket (ROADMAP item-1 follow-up; the
    bench_moe_a2a docstring documents the same artifact). Real TPU
    keeps the kernel: the compiled Pallas call partitions cleanly and
    the streaming-CE memory win is the whole point there. Both trace-
    time probes fail CLOSED (``ambient_gspmd_mesh`` returns None on
    any API drift, and inside shard_map bodies — where the fused
    kernel is the right choice — every mesh axis is Manual, so the
    mesh probe reads None and the kernel stays)."""
    lm_shaped = preds.ndim == 3 and not (
        jnp.issubdtype(targets.dtype, jnp.floating) and targets.shape == preds.shape
    )
    if lm_shaped:
        from sparktorch_tpu.parallel.compat import ambient_gspmd_mesh

        if jax.default_backend() != "tpu" \
                and ambient_gspmd_mesh() is not None:
            return cross_entropy_loss(preds, targets)
        return fused_cross_entropy_loss(preds, targets)
    return cross_entropy_loss(preds, targets)


def nll_loss(preds: jax.Array, targets: jax.Array) -> jax.Array:
    """Negative log-likelihood on already-log-probability inputs."""
    labels = targets.astype(jnp.int32)
    if labels.ndim == preds.ndim:
        labels = labels.reshape(labels.shape[:-1])
    picked = jnp.take_along_axis(preds, labels[..., None], axis=-1)[..., 0]
    return -picked.reshape(preds.shape[0], -1).mean(-1)


def bce_with_logits_loss(preds: jax.Array, targets: jax.Array) -> jax.Array:
    preds, targets = _align(preds, targets)
    # Numerically-stable sigmoid BCE.
    per = jnp.maximum(preds, 0) - preds * targets + jnp.log1p(jnp.exp(-jnp.abs(preds)))
    return _flatten_per_example(per)


LOSS_REGISTRY: dict[str, LossFn] = {
    "mse": mse_loss,
    "l1": l1_loss,
    "mae": l1_loss,
    "huber": huber_loss,
    "smooth_l1": huber_loss,
    "cross_entropy": cross_entropy_auto,
    "cross_entropy_dense": cross_entropy_loss,
    "cross_entropy_fused": fused_cross_entropy_loss,
    "nll": nll_loss,
    "bce_with_logits": bce_with_logits_loss,
    # torch.nn criterion-class spellings, so reference users can pass the
    # names they know (util.py:30-32 pickles e.g. nn.MSELoss()).
    "MSELoss": mse_loss,
    "L1Loss": l1_loss,
    "SmoothL1Loss": huber_loss,
    "CrossEntropyLoss": cross_entropy_auto,
    "NLLLoss": nll_loss,
    "BCEWithLogitsLoss": bce_with_logits_loss,
}


def resolve_loss(loss: Union[str, LossFn]) -> LossFn:
    if callable(loss):
        return loss
    try:
        return LOSS_REGISTRY[loss]
    except KeyError:
        raise ValueError(
            f"Unknown loss {loss!r}; known: {sorted(LOSS_REGISTRY)} or pass a callable"
        ) from None
