"""Model/optimizer/loss packaging — the L1 serde core.

Reference capability being reproduced (``sparktorch/util.py``):

- ``TorchObj`` namedtuple + dill/base64 encode/decode (``util.py:30-54``)
- ``serialize_torch_obj`` — eager packaging into a JSON envelope
  ``{'torch_obj': <b64 dill>, 'shapes': [param shapes]}``
  (``util.py:182-201``)
- ``serialize_torch_obj_lazy`` — *classes* + ctor kwargs are shipped so
  the model is first instantiated on the workers and the driver never
  holds weights (``util.py:148-179``; README.md:115-132)
- ``load_base_torch`` / ``load_torch_model`` / ``load_optimizer``
  (``util.py:103-145,204-208``)

TPU-native redesign:

- The payload is a :class:`ModelSpec` describing a Flax module, a pure
  loss fn and an optax optimizer — all functional, so "lazy" is the
  *default* posture: parameters are created by ``module.init`` on the
  worker, directly under the device mesh's sharding.
- Shape recording uses ``jax.eval_shape`` — abstract tracing, zero
  FLOPs, zero host memory for weights. This is strictly stronger than
  the reference's lazy path, which still builds a temp model on the
  driver to read shapes (``util.py:164-165``).
- The JSON envelope keeps the reference's two-field contract
  (payload + shapes) so external tooling that inspects the envelope
  keeps working; the shapes field is what the reference's phantom
  rank consumed (``distributed.py:239-246``) and what our parameter
  server uses to preallocate HBM buffers.
"""

from __future__ import annotations

import base64
import codecs
import dataclasses
import json
from typing import Any, Callable, Mapping, Optional, Sequence, Tuple, Union

import dill
import jax
import jax.numpy as jnp
import numpy as np
import optax

from sparktorch_tpu.utils.losses import LossFn, resolve_loss

ENVELOPE_VERSION = 1

# ---------------------------------------------------------------------------
# Optimizer registry: name -> optax ctor. torch.optim spellings are accepted
# (with their hyperparameter names mapped) so reference users can keep their
# configs; util.py:204-208 binds a torch optimizer class the same way.
# ---------------------------------------------------------------------------

_TORCH_PARAM_MAP = {"lr": "learning_rate", "weight_decay": "weight_decay"}


def _map_opt_kwargs(kwargs: Mapping[str, Any]) -> dict:
    out = {}
    for k, v in kwargs.items():
        out[_TORCH_PARAM_MAP.get(k, k)] = v
    return out


def _sgd(learning_rate=0.01, momentum=0.0, nesterov=False, **kw):
    return optax.sgd(learning_rate, momentum=momentum or None, nesterov=nesterov)


OPTIMIZER_REGISTRY: dict[str, Callable[..., optax.GradientTransformation]] = {
    "sgd": _sgd,
    "adam": optax.adam,
    "adamw": optax.adamw,
    "rmsprop": optax.rmsprop,
    "adagrad": optax.adagrad,
    "adafactor": optax.adafactor,
    "lamb": optax.lamb,
    "lion": optax.lion,
    # torch.optim class-name spellings.
    "SGD": _sgd,
    "Adam": optax.adam,
    "AdamW": optax.adamw,
    "RMSprop": optax.rmsprop,
    "Adagrad": optax.adagrad,
}

# torch.optim ctor default lrs (the reference binds the torch class
# with whatever kwargs the user gave — util.py:204-208 — so `Adam`
# with no params trains at torch's default 1e-3; optax ctors take
# learning_rate positionally and would TypeError instead). Only names
# that exist in torch.optim get a default — optax-only optimizers
# (lamb, lion) keep the loud missing-lr error.
_TORCH_DEFAULT_LR: dict[str, float] = {
    "adam": 1e-3, "Adam": 1e-3, "adamw": 1e-3, "AdamW": 1e-3,
    "rmsprop": 1e-2, "RMSprop": 1e-2, "adagrad": 1e-2, "Adagrad": 1e-2,
}


def resolve_optimizer(
    optimizer: Union[str, Callable, optax.GradientTransformation, None],
    optimizer_params: Optional[Mapping[str, Any]] = None,
) -> optax.GradientTransformation:
    """Bind an optimizer spec to an optax GradientTransformation.

    Parity: ``util.py:204-208`` (``load_optimizer`` binding a torch
    optimizer class to params). optax transformations are param-free
    until ``init``, so binding is just construction.
    """
    params = _map_opt_kwargs(optimizer_params or {})
    if optimizer is None:
        return optax.sgd(params.pop("learning_rate", 0.01))
    if isinstance(optimizer, optax.GradientTransformation):
        return optimizer
    if isinstance(optimizer, str):
        try:
            ctor = OPTIMIZER_REGISTRY[optimizer]
        except KeyError:
            raise ValueError(
                f"Unknown optimizer {optimizer!r}; known: {sorted(OPTIMIZER_REGISTRY)}"
            ) from None
        if optimizer in _TORCH_DEFAULT_LR:
            params.setdefault("learning_rate", _TORCH_DEFAULT_LR[optimizer])
        return ctor(**params)
    # A callable ctor (e.g. optax.adam itself, or a user factory).
    return optimizer(**params)


# ---------------------------------------------------------------------------
# ModelSpec
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ModelSpec:
    """The unit of model shipment — analog of ``TorchObj`` (util.py:30-32).

    Exactly one of ``module`` (eager) or ``module_cls`` (lazy) is set.
    ``loss`` is a registry name or a pure ``(preds, targets) ->
    per-example loss`` callable. ``optimizer`` may be a registry name,
    an optax transformation, or a ctor; name+params is the most
    portable (it round-trips through dill without closures).
    """

    module: Any = None
    module_cls: Optional[type] = None
    module_kwargs: dict = dataclasses.field(default_factory=dict)
    loss: Union[str, LossFn] = "mse"
    optimizer: Union[str, Callable, optax.GradientTransformation, None] = "sgd"
    optimizer_params: dict = dataclasses.field(default_factory=dict)
    input_shape: Optional[Tuple[int, ...]] = None  # per-example, no batch dim
    input_dtype: str = "float32"
    is_lazy: bool = False
    param_shapes: Optional[list] = None  # recorded at serialize time

    # -- materialization (worker side) ------------------------------------

    def make_module(self):
        if self.module is not None:
            return self.module
        if self.module_cls is None:
            raise ValueError("ModelSpec has neither module nor module_cls")
        return self.module_cls(**self.module_kwargs)

    def loss_fn(self) -> LossFn:
        return resolve_loss(self.loss)

    def make_optimizer(self) -> optax.GradientTransformation:
        return resolve_optimizer(self.optimizer, self.optimizer_params)

    def example_input(self, batch_size: int = 1) -> jax.ShapeDtypeStruct:
        if self.input_shape is None:
            raise ValueError("ModelSpec.input_shape not set")
        return jax.ShapeDtypeStruct(
            (batch_size,) + tuple(self.input_shape), jnp.dtype(self.input_dtype)
        )

    def init_params(self, rng: jax.Array, sample_x: Optional[jax.Array] = None):
        """Instantiate parameters on this process's devices.

        Parity: ``load_torch_model`` lazy instantiation
        (``util.py:125-134``) — but params come out of ``module.init``
        already placed per the active mesh context.
        """
        module = self.make_module()
        if sample_x is None:
            spec = self.example_input()
            sample_x = jnp.zeros(spec.shape, spec.dtype)
        variables = module.init(rng, sample_x)
        return variables

    def abstract_params(self, rng: Optional[jax.Array] = None):
        """Shapes/dtypes of the param pytree with ZERO allocation.

        The driver-side analog of the reference's shape recording
        (``util.py:164-165,196-199``) — consumed by the parameter
        server and the shapes field of the envelope.
        """
        module = self.make_module()
        spec = self.example_input()
        key = rng if rng is not None else jax.random.key(0)
        return jax.eval_shape(
            lambda k, x: module.init(k, x),
            key,
            spec,
        )


# ---------------------------------------------------------------------------
# Encode / decode (dill + base64, JSON envelope) — util.py:37-54,182-201
# ---------------------------------------------------------------------------


def spec_encoder(obj: Any) -> str:
    """dill -> base64 str. Parity: ``torch_encoder`` (util.py:37-43)."""
    return codecs.encode(dill.dumps(obj), "base64").decode()


def spec_decoder(s: str) -> Any:
    """base64 str -> object. Parity: ``torch_decoder`` (util.py:46-54)."""
    if isinstance(s, str):
        s = s.encode()
    return dill.loads(codecs.decode(s, "base64"))


def _shapes_of(spec: ModelSpec) -> Optional[list]:
    if spec.input_shape is None:
        return None
    abstract = spec.abstract_params()
    return [list(leaf.shape) for leaf in jax.tree.leaves(abstract)]


def _envelope(spec: ModelSpec) -> str:
    spec.param_shapes = _shapes_of(spec)
    return json.dumps(
        {
            "torch_obj": spec_encoder(spec),  # field name kept for envelope parity
            "shapes": spec.param_shapes,
            "version": ENVELOPE_VERSION,
            "framework": "sparktorch_tpu",
        }
    )


def serialize_model(
    model: Any,
    criterion: Union[str, LossFn] = "mse",
    optimizer: Union[str, Callable, optax.GradientTransformation, None] = "sgd",
    optimizer_params: Optional[Mapping[str, Any]] = None,
    input_shape: Optional[Sequence[int]] = None,
    input_dtype: str = "float32",
) -> str:
    """Eagerly package a Flax module + loss + optimizer.

    Parity: ``serialize_torch_obj`` (util.py:182-201). The module
    *object* is shipped (its hyperparameters; Flax modules carry no
    weights), the loss is a name or pure fn, the optimizer a name/ctor
    with params.
    """
    spec = ModelSpec(
        module=model,
        loss=criterion,
        optimizer=optimizer,
        optimizer_params=dict(optimizer_params or {}),
        input_shape=tuple(input_shape) if input_shape is not None else None,
        input_dtype=input_dtype,
        is_lazy=False,
    )
    return _envelope(spec)


def serialize_model_lazy(
    model: type,
    criterion: Union[str, LossFn] = "mse",
    optimizer: Union[str, Callable, None] = "sgd",
    optimizer_params: Optional[Mapping[str, Any]] = None,
    model_parameters: Optional[Mapping[str, Any]] = None,
    input_shape: Optional[Sequence[int]] = None,
    input_dtype: str = "float32",
) -> str:
    """Package a module *class* + ctor kwargs; instantiation happens on
    workers so the driver never holds weights.

    Parity: ``serialize_torch_obj_lazy`` (util.py:148-179). Shapes are
    recorded abstractly via ``jax.eval_shape`` rather than by building
    a temporary model (the reference's ``util.py:164-165``).
    """
    spec = ModelSpec(
        module_cls=model,
        module_kwargs=dict(model_parameters or {}),
        loss=criterion,
        optimizer=optimizer,
        optimizer_params=dict(optimizer_params or {}),
        input_shape=tuple(input_shape) if input_shape is not None else None,
        input_dtype=input_dtype,
        is_lazy=True,
    )
    return _envelope(spec)


def deserialize_model(payload: Union[str, ModelSpec]) -> ModelSpec:
    """Envelope/b64 string -> ModelSpec.

    Parity: ``load_base_torch`` + ``load_torch_model``
    (util.py:103-145). Accepts the JSON envelope, a bare base64 dill
    string, or an already-decoded ModelSpec (idempotent).
    """
    if isinstance(payload, ModelSpec):
        return payload
    text = payload.strip()
    if text.startswith("{"):
        env = json.loads(text)
        spec = spec_decoder(env["torch_obj"])
        spec.param_shapes = env.get("shapes")
        return spec
    return spec_decoder(text)


def envelope_shapes(payload: str) -> Optional[list]:
    """Read param shapes from the envelope WITHOUT unpickling.

    The reference's phantom rank consumed exactly this
    (``load_base_torch`` -> shapes, util.py:103-110;
    ``distributed.py:239-246``); our parameter server uses it to
    preallocate buffers before any worker connects.
    """
    text = payload.strip()
    if not text.startswith("{"):
        return None
    return json.loads(text).get("shapes")


# Reference-compatible export names (sparktorch/__init__.py:1-4).
serialize_torch_obj = serialize_model
serialize_torch_obj_lazy = serialize_model_lazy
