"""Feature batching, validation split, shard-safe padding.

Reference capability (``sparktorch/util.py``):

- ``DataObj(x_train, y_train, x_val, y_val)`` per-row container
  (``util.py:34``) built row-wise by ``handle_data``
  (``torch_distributed.py:43-55``)
- ``handle_features`` stacks per-row numpy arrays into batch tensors
  and does a random validation split (``util.py:57-100``)

TPU-native redesign:

- :class:`DataBatch` is a *batched* (x, y, w) triple. ``w`` is a
  per-example weight used for (a) masking padding rows and (b) the
  empty-shard protocol: a device shard with no real data carries an
  all-zero-weight batch, so the globally-weighted loss/grad mean is
  unaffected while every device still enters the same collectives.
  This replaces the reference's phantom-rank / ``process_generic_model``
  zero-gradient mock participant (``distributed.py:46-63,131-133``).
- Batches are padded to a common static shape per shard: XLA requires
  static shapes; ragged partitions become weight-masked padding instead
  of the dynamic per-partition sizes the reference tolerates.
"""

from __future__ import annotations

from typing import Iterable, NamedTuple, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np


class DataBatch(NamedTuple):
    """Batched examples. ``y`` may equal ``x`` (autoencoder, label-free
    mode — the reference's ``useVectorOut``/no-label path,
    ``torch_distributed.py:45-55``). ``w`` is float32 (batch,)."""

    x: jax.Array
    y: jax.Array
    w: jax.Array

    @property
    def size(self) -> int:
        return self.x.shape[0]

    def real_count(self) -> jax.Array:
        return jnp.sum(self.w)


def _stack_rows(
    rows: Sequence, has_label: bool
) -> Tuple[np.ndarray, Optional[np.ndarray]]:
    xs, ys = [], []
    for row in rows:
        if has_label:
            x, y = row
            ys.append(np.asarray(y))
        else:
            x = row
        xs.append(np.asarray(x, dtype=np.float32))
    x = np.stack(xs) if xs else np.zeros((0, 1), np.float32)
    y = np.stack(ys) if ys else None
    return x, y


def handle_features(
    data: Union[Iterable, np.ndarray],
    labels: Optional[np.ndarray] = None,
    validation_pct: float = 0.0,
    seed: int = 0,
) -> Tuple[DataBatch, Optional[DataBatch]]:
    """Stack rows into a train batch (+ optional validation batch).

    Parity: ``handle_features`` (util.py:57-100) — numpy stacking plus
    a random validation split. Accepts either parallel ``data``/
    ``labels`` arrays or an iterable of ``(x, y)`` rows / bare ``x``
    rows (the reference's ``DataObj`` stream).
    """
    if labels is None and not isinstance(data, np.ndarray):
        rows = list(data)
        if rows and isinstance(rows[0], tuple) and len(rows[0]) == 2:
            x, y = _stack_rows(rows, has_label=True)
        else:
            x, y = _stack_rows(rows, has_label=False)
    else:
        x = np.asarray(data, dtype=np.float32)
        y = np.asarray(labels) if labels is not None else None

    if y is None:
        y = x  # label-free / autoencoder target (util.py:69-74 analog)

    n = x.shape[0]
    w = np.ones((n,), np.float32)
    if validation_pct and validation_pct > 0.0 and n > 1:
        rng = np.random.default_rng(seed)
        perm = rng.permutation(n)
        n_val = max(1, int(n * validation_pct))
        val_idx, train_idx = perm[:n_val], perm[n_val:]
        train = DataBatch(
            jnp.asarray(x[train_idx]), jnp.asarray(y[train_idx]), jnp.asarray(w[train_idx])
        )
        val = DataBatch(
            jnp.asarray(x[val_idx]), jnp.asarray(y[val_idx]), jnp.asarray(w[val_idx])
        )
        return train, val
    return DataBatch(jnp.asarray(x), jnp.asarray(y), jnp.asarray(w)), None


def pad_batch(batch: DataBatch, to_size: int) -> DataBatch:
    """Zero-pad to a static size; padding rows get weight 0."""
    n = batch.size
    if n == to_size:
        return batch
    if n > to_size:
        raise ValueError(f"batch of {n} cannot be padded down to {to_size}")
    pad = to_size - n

    def _pad(a):
        widths = [(0, pad)] + [(0, 0)] * (a.ndim - 1)
        return jnp.pad(a, widths)

    return DataBatch(_pad(batch.x), _pad(batch.y), _pad(batch.w))


def empty_batch(x_shape: Sequence[int], y_shape: Sequence[int],
                batch_size: int, x_dtype=jnp.float32, y_dtype=jnp.float32) -> DataBatch:
    """An all-padding batch for a shard with no data — the empty-
    partition safety valve (``distributed.py:131-133`` analog)."""
    return DataBatch(
        jnp.zeros((batch_size, *x_shape), x_dtype),
        jnp.zeros((batch_size, *y_shape), y_dtype),
        jnp.zeros((batch_size,), jnp.float32),
    )


def pad_to_multiple(batch: DataBatch, multiple: int) -> DataBatch:
    """Pad so the batch divides evenly across ``multiple`` shards."""
    n = batch.size
    target = max(multiple, ((n + multiple - 1) // multiple) * multiple)
    return pad_batch(batch, target)


def sample_minibatch(
    batch: DataBatch, rng: jax.Array, mini_batch: int
) -> DataBatch:
    """Minibatch sampling traceable under jit: a contiguous block at a
    uniform random offset of the resident shard.

    The reference samples row indices per step
    (``distributed.py:146-149``). Reproducing that on TPU with a
    permutation + gather is pathological: a gather of random rows is
    scattered HBM DMA, measured ~15x slower than the gradient step it
    feeds. A contiguous ``dynamic_slice`` is bandwidth-optimal and
    keeps the whole step one fused program. Within a step the rows of
    a block are correlated, but the trainers reshuffle the resident
    shard between rounds (``_shuffle_batch`` / the driver's host-side
    permutation), so across steps this is uniform block sampling —
    without-replacement at epoch granularity, the same regime the
    reference's per-partition sampling lives in. Weight-0 padding rows
    inside a block are absorbed by the weighted-mean loss like
    everywhere else.
    """
    n = batch.size
    off = jax.random.randint(rng, (), 0, n - mini_batch + 1)

    def sl(a):
        return jax.lax.dynamic_slice_in_dim(a, off, mini_batch)

    return DataBatch(sl(batch.x), sl(batch.y), sl(batch.w))
