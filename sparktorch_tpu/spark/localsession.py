"""A pyspark-API-compatible local runtime — no JVM, real processes.

Why this exists: the reference's entire test tier is "integration
tests against a real local Spark session" (reference
``tests/test_sparktorch.py:13-26``: ``local[2]`` + 2 partitions, the
minimal world where barrier execution is real). This image has no
pyspark, so without an equivalent the whole ``sparktorch_tpu.spark``
deployment tier would be untestable dead weight. This module is that
equivalent: a faithful miniature of the pyspark surface the adapter
uses, with the load-bearing property that **mapPartitions tasks run
in separate OS processes** (closures shipped with dill, one process
per partition, gang-launched for barrier RDDs) — so the gang
coordinator's TCP rendezvous, ``jax.distributed`` multi-process
bring-up and the hogwild HTTP wire are exercised for real, not
faked in-process.

``install()`` registers these classes under the module names the
adapter imports (``pyspark``, ``pyspark.ml`` ...) ONLY when real
pyspark is absent — with pyspark installed this module stays inert,
and the adapter code runs unmodified against the real thing.

Implemented subset (what ``torch_distributed.py`` + ``pipeline_util
.py`` + the reference test flows touch): SparkSession/builder/conf,
columnar DataFrame (select/withColumn/collect/schema/rdd), RDD
(mapPartitions/repartition/barrier/collect/foreach),
BarrierTaskContext, broadcast, pandas_udf, DenseVector/VectorUDT/
vector_to_array, StopWordsRemover, Pipeline/PipelineModel with
directory persistence that honors the ``_to_carrier`` hook (the
shim analog of pyspark's ``_to_java`` JavaMLWriter hook).
"""

from __future__ import annotations

import json
import os
import re
import subprocess
import sys
import tempfile
import uuid
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from sparktorch_tpu.ml.params import (
    Param,
    Params,
    TypeConverters,
    keyword_only,
)

_EXECUTOR_TIMEOUT_S = 600.0


# ---------------------------------------------------------------------------
# Rows / vectors / SQL types
# ---------------------------------------------------------------------------


class Row(tuple):
    """Indexable by position, column name, or attribute — the access
    patterns the adapter uses (``r[0]``, ``r['predictions']``)."""

    def __new__(cls, values: Sequence, fields: Sequence[str]):
        self = super().__new__(cls, values)
        self._fields = tuple(fields)
        return self

    def __getitem__(self, key):
        if isinstance(key, str):
            return super().__getitem__(self._fields.index(key))
        return super().__getitem__(key)

    def __getattr__(self, name):
        fields = object.__getattribute__(self, "_fields")
        if name in fields:
            return tuple.__getitem__(self, fields.index(name))
        raise AttributeError(name)

    def asDict(self) -> dict:
        return {f: tuple.__getitem__(self, i) for i, f in enumerate(self._fields)}

    def __reduce__(self):
        return (Row, (tuple(self), self._fields))


class DenseVector:
    """pyspark.ml.linalg.DenseVector lookalike."""

    def __init__(self, values):
        self._values = np.asarray(values, dtype=np.float64)

    def toArray(self) -> np.ndarray:
        return self._values

    def __len__(self):
        return len(self._values)

    def __iter__(self):
        return iter(self._values)

    def __repr__(self):
        return f"DenseVector({self._values.tolist()})"


class Vectors:
    @staticmethod
    def dense(*values) -> DenseVector:
        if len(values) == 1 and np.ndim(values[0]) >= 1:
            return DenseVector(values[0])
        return DenseVector(values)


class VectorUDT:
    def __eq__(self, other):
        return isinstance(other, VectorUDT)

    def __hash__(self):
        return hash("VectorUDT")


class DoubleType:
    pass


class FloatType:
    pass


class ArrayType:
    def __init__(self, elementType=None, containsNull=True):
        self.elementType = elementType
        self.containsNull = containsNull


class StructField:
    def __init__(self, name: str, dataType, nullable: bool = True):
        self.name = name
        self.dataType = dataType
        self.nullable = nullable


class StructType:
    def __init__(self, fields: List[StructField]):
        self.fields = fields

    def __getitem__(self, name: str) -> StructField:
        for f in self.fields:
            if f.name == name:
                return f
        raise KeyError(name)


def _infer_type(value):
    if isinstance(value, DenseVector):
        return VectorUDT()
    if isinstance(value, (list, np.ndarray)):
        return ArrayType(DoubleType())
    return DoubleType()


# ---------------------------------------------------------------------------
# Columns and pandas UDFs
# ---------------------------------------------------------------------------


class Column:
    """A lazy reference to a source column plus a value converter
    chain (``vector_to_array``) and optionally a pandas UDF."""

    def __init__(self, name: str, conv: Optional[Callable] = None,
                 udf: Optional["_PandasUdf"] = None):
        self.name = name
        self.conv = conv
        self.udf = udf


def vector_to_array(col: Column) -> Column:
    def conv(values):
        return [
            np.asarray(v.toArray() if hasattr(v, "toArray") else v,
                       dtype=np.float64)
            for v in values
        ]

    return Column(col.name, conv=conv, udf=col.udf)


class _PandasUdf:
    def __init__(self, fn: Callable, returnType):
        self.fn = fn
        self.returnType = returnType

    def __call__(self, col: Column) -> Column:
        return Column(col.name, conv=col.conv, udf=self)


def pandas_udf(returnType, functionType=None):
    def deco(fn):
        return _PandasUdf(fn, returnType)

    return deco


# ---------------------------------------------------------------------------
# DataFrame
# ---------------------------------------------------------------------------


class DataFrame:
    def __init__(self, cols: Dict[str, list], session: "SparkSession",
                 npartitions: int = 2):
        self._cols = {k: list(v) for k, v in cols.items()}
        ns = {len(v) for v in self._cols.values()}
        if len(ns) > 1:
            raise ValueError(f"ragged columns: { {k: len(v) for k, v in self._cols.items()} }")
        self._n = ns.pop() if ns else 0
        self.sparkSession = session
        self._npartitions = max(1, npartitions)

    @property
    def columns(self) -> List[str]:
        return list(self._cols)

    @property
    def schema(self) -> StructType:
        return StructType([
            StructField(name, _infer_type(vals[0]) if vals else DoubleType())
            for name, vals in self._cols.items()
        ])

    def __getitem__(self, name: str) -> Column:
        if name not in self._cols:
            raise KeyError(name)
        return Column(name)

    def count(self) -> int:
        return self._n

    def select(self, *names) -> "DataFrame":
        names = [n.name if isinstance(n, Column) else n for n in names]
        return DataFrame({n: self._cols[n] for n in names}, self.sparkSession,
                         self._npartitions)

    def repartition(self, n: int) -> "DataFrame":
        return DataFrame(self._cols, self.sparkSession, n)

    def collect(self) -> List[Row]:
        fields = list(self._cols)
        return [
            Row([self._cols[f][i] for f in fields], fields)
            for i in range(self._n)
        ]

    def take(self, n: int) -> List[Row]:
        return self.collect()[:n]

    @property
    def rdd(self) -> "RDD":
        fields = list(self._cols)
        rows = [
            Row([self._cols[f][i] for f in fields], fields)
            for i in range(self._n)
        ]
        return RDD(rows, self._npartitions, self.sparkSession)

    def withColumn(self, name: str, col: Column) -> "DataFrame":
        if not isinstance(col, Column) or col.udf is None:
            raise TypeError("withColumn expects a pandas_udf column")
        import pandas as pd

        values = self._cols[col.name]
        if col.conv is not None:
            values = col.conv(values)
        # Evaluate in >=2 batches when possible: faithful to Arrow's
        # chunked evaluation, and catches UDFs that assume one call.
        chunks = []
        n_chunks = 2 if self._n >= 2 else 1
        for part in np.array_split(np.arange(self._n), n_chunks):
            if len(part) == 0:
                continue
            series = pd.Series([values[i] for i in part])
            out = col.udf.fn(series)
            chunks.extend(list(out))
        new_cols = dict(self._cols)
        new_cols[name] = chunks
        return DataFrame(new_cols, self.sparkSession, self._npartitions)


# ---------------------------------------------------------------------------
# RDD with real-process executors
# ---------------------------------------------------------------------------


class BarrierTaskContext:
    """Executor-side context; set up by the executor bootstrap."""

    _current: Optional["BarrierTaskContext"] = None

    def __init__(self, partition_id: int, world: int):
        self._partition_id = partition_id
        self._world = world

    @classmethod
    def get(cls) -> "BarrierTaskContext":
        if cls._current is None:
            raise RuntimeError("not inside a barrier task")
        return cls._current

    def partitionId(self) -> int:
        return self._partition_id

    def getTaskInfos(self):
        return [{"address": "127.0.0.1"} for _ in range(self._world)]

    def barrier(self):  # tasks are gang-launched; nothing to wait on
        return None


def _split_partitions(rows: List, n: int) -> List[List]:
    # array_split's chunking without numpy coercion (Rows are tuples —
    # np.asarray would explode them into a 2-D object array).
    bounds = np.linspace(0, len(rows), n + 1).astype(int)
    return [rows[a:b] for a, b in zip(bounds[:-1], bounds[1:])]


def _executor_env(n_devices: int = 1) -> Dict[str, str]:
    """Child env: scrub any forced host-device count (the test conftest
    forces 8) and pin the platform to CPU — one device per executor by
    default, so N barrier tasks form an N-device multi-process world."""
    env = dict(os.environ)
    flags = env.get("XLA_FLAGS", "")
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "", flags)
    if "--xla_cpu_enable_concurrency_optimized_scheduler" not in flags:
        # Program-order thunk scheduling on the virtual-device rig —
        # the concurrent scheduler flakily mixes same-shape collective
        # rendezvous of one launch (see tests/conftest.py).
        flags += " --xla_cpu_enable_concurrency_optimized_scheduler=false"
    env["XLA_FLAGS"] = f"{flags} --xla_force_host_platform_device_count={n_devices}"
    env["JAX_PLATFORMS"] = "cpu"
    repo = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    return env


class RDD:
    def __init__(self, rows: List, npartitions: int, session: "SparkSession",
                 fns: Optional[List[Callable]] = None, is_barrier: bool = False):
        self._rows = rows
        self._npartitions = max(1, npartitions)
        self._session = session
        self._fns = fns or []
        self._is_barrier = is_barrier

    def getNumPartitions(self) -> int:
        return self._npartitions

    def repartition(self, n: int) -> "RDD":
        return RDD(self._rows, n, self._session, self._fns, self._is_barrier)

    def barrier(self) -> "RDD":
        return RDD(self._rows, self._npartitions, self._session, self._fns, True)

    def mapPartitions(self, fn: Callable) -> "RDD":
        return RDD(self._rows, self._npartitions, self._session,
                   self._fns + [fn], self._is_barrier)

    def foreach(self, fn: Callable) -> None:
        # An action: evaluate everything, discard results
        # (the reference's hogwild trigger, hogwild.py:161-173).
        self.mapPartitions(lambda it: [fn(x) for x in it]).collect()

    def collect(self) -> List:
        if not self._fns:
            return list(self._rows)
        return self._run_executors()

    def _run_executors(self) -> List:
        """One OS process per partition, launched concurrently (the
        gang — every barrier task starts before any is waited on),
        closures shipped via dill like Spark ships them to its Python
        workers."""
        import dill

        parts = _split_partitions(self._rows, self._npartitions)

        def chained(iterator, _fns=self._fns):
            out = iterator
            for f in _fns:
                out = f(out)
            return list(out)

        import shutil
        import time as _time

        tmpdir = tempfile.mkdtemp(prefix="localspark_")
        try:
            procs = []
            for idx, rows in enumerate(parts):
                payload_path = os.path.join(tmpdir, f"task{idx}.in")
                result_path = os.path.join(tmpdir, f"task{idx}.out")
                log_path = os.path.join(tmpdir, f"task{idx}.log")
                with open(payload_path, "wb") as f:
                    # JSON header first: the executor must extend
                    # sys.path BEFORE unpickling (dill resolves closure
                    # modules by import — Spark likewise requires user
                    # code importable on its workers).
                    f.write(json.dumps({"sys_path": sys.path}).encode() + b"\n")
                    dill.dump(
                        {
                            "fn": chained,
                            "rows": rows,
                            "partition_id": idx,
                            "world": self._npartitions,
                            "barrier": self._is_barrier,
                        },
                        f,
                        recurse=False,
                    )
                # Task output goes to a FILE, not a pipe: a chatty
                # executor must never block on a full pipe buffer while
                # the driver waits on a different task — in barrier
                # mode that would stall the whole gang.
                log_f = open(log_path, "w")
                proc = subprocess.Popen(
                    [sys.executable, "-m", "sparktorch_tpu.spark._executor",
                     payload_path, result_path],
                    env=_executor_env(),
                    stdout=log_f,
                    stderr=subprocess.STDOUT,
                )
                procs.append((idx, proc, result_path, log_path, log_f))

            results: List = []
            errors: List[str] = []
            deadline = _time.monotonic() + _EXECUTOR_TIMEOUT_S
            for idx, proc, result_path, log_path, log_f in procs:
                try:
                    proc.wait(timeout=max(1.0, deadline - _time.monotonic()))
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait()
                log_f.close()
                if proc.returncode != 0:
                    with open(log_path) as f:
                        tail = f.read()[-4000:]
                    word = "timed out" if proc.returncode == -9 else (
                        f"failed (rc={proc.returncode})"
                    )
                    errors.append(f"task {idx} {word}\n{tail}")
                    continue
                with open(result_path, "rb") as f:
                    results.extend(dill.load(f))
            if errors:
                raise RuntimeError(
                    "localspark executor failure:\n" + "\n---\n".join(errors)
                )
            return results
        finally:
            shutil.rmtree(tmpdir, ignore_errors=True)


# ---------------------------------------------------------------------------
# Session
# ---------------------------------------------------------------------------


class Broadcast:
    def __init__(self, value):
        self.value = value

    def unpersist(self):
        pass


class _JavaArray(list):
    """Fixed-length array as py4j's ``gateway.new_array`` returns —
    supports the slice assignment the carrier encoder uses
    (reference ``pipeline_util.py:125``)."""

    def __init__(self, n: int):
        super().__init__([None] * n)


class _JavaString:
    """Token standing in for ``gateway.jvm.java.lang.String``."""


class _JavaLang:
    String = _JavaString


class _Java:
    lang = _JavaLang


class _Jvm:
    java = _Java


class _Gateway:
    """The slice of the py4j gateway surface the carrier encoder
    touches (``sc._gateway.jvm.java.lang.String`` +
    ``sc._gateway.new_array``). With real pyspark these calls cross
    into the JVM; here they hit this protocol-faithful local stand-in,
    so the SAME ``_to_java`` code path executes in both runtimes."""

    jvm = _Jvm

    def new_array(self, java_class, n: int) -> _JavaArray:
        return _JavaArray(n)


class _JavaStopWordsRemover:
    """The object ``JavaParams._new_java_obj`` would materialize in the
    JVM (``org.apache.spark.ml.feature.StopWordsRemover``): carries a
    uid and a stopwords array."""

    def __init__(self, uid: str):
        self._uid = uid
        self._stopWords: list = []

    def setStopWords(self, arr):
        self._stopWords = [w for w in arr]
        return self

    def getStopWords(self):
        return list(self._stopWords)

    def uid(self):
        return self._uid


class JavaParams:
    """pyspark.ml.wrapper.JavaParams subset: the ``_new_java_obj``
    factory the carrier encoder calls (reference
    ``pipeline_util.py:126``)."""

    _CARRIER_JAVA_CLASS = "org.apache.spark.ml.feature.StopWordsRemover"

    @staticmethod
    def _new_java_obj(java_class: str, *args):
        if java_class != JavaParams._CARRIER_JAVA_CLASS:
            raise ValueError(
                f"localspark gateway only materializes the carrier class, "
                f"not {java_class!r}"
            )
        uid = args[0] if args else f"StopWordsRemover_{uuid.uuid4().hex[:12]}"
        return _JavaStopWordsRemover(uid)


class JavaMLWriter:
    """Stage-level writer driving the instance's ``_to_java`` hook —
    the same contract as pyspark's JavaMLWriter (which the reference
    returns from ``write()``, ``pipeline_util.py:88-90``): convert to
    the JVM-persistable carrier, save it under ``path``."""

    def __init__(self, instance):
        self._instance = instance
        self._overwrite = False

    def overwrite(self) -> "JavaMLWriter":
        self._overwrite = True
        return self

    def session(self, _session) -> "JavaMLWriter":
        return self

    def save(self, path: str) -> None:
        jobj = self._instance._to_java()
        if os.path.exists(path) and not self._overwrite:
            raise FileExistsError(path)
        os.makedirs(path, exist_ok=True)
        meta = {
            "class": JavaParams._CARRIER_JAVA_CLASS,
            "uid": jobj.uid(),
            "stopWords": jobj.getStopWords(),
        }
        with open(os.path.join(path, "metadata.json"), "w") as f:
            json.dump(meta, f)  # lint-obs: ok (JVM-parity metadata)


class JavaMLReader:
    """Reads a saved carrier stage back as the carrier class instance
    (pyspark's ``JavaMLReader(StopWordsRemover).load`` contract — the
    reference's ``read()``, ``pipeline_util.py:92-95``)."""

    def __init__(self, clazz):
        self._clazz = clazz

    def load(self, path: str):
        with open(os.path.join(path, "metadata.json")) as f:
            meta = json.load(f)
        if meta.get("class") != JavaParams._CARRIER_JAVA_CLASS:
            raise ValueError(f"not a carrier stage dir: {path}")
        stage = self._clazz()
        stage.uid = meta["uid"]
        stage.setStopWords(meta["stopWords"])
        return stage


class MLReadable:
    """Marker mixin, parity with pyspark.ml.util.MLReadable."""


class MLWritable:
    """Marker mixin, parity with pyspark.ml.util.MLWritable."""


class Identifiable:
    """Marker mixin, parity with pyspark.ml.util.Identifiable."""


class SparkContext:
    # Real pyspark exposes the active context (and its py4j gateway)
    # here; the carrier encoder reads it (reference
    # pipeline_util.py:120). Set while a SparkSession is alive.
    _active_spark_context: Optional["SparkContext"] = None

    def __init__(self):
        self._gateway = _Gateway()

    def broadcast(self, value) -> Broadcast:
        return Broadcast(value)


class _RuntimeConf:
    def __init__(self):
        self._conf = {"spark.driver.host": "127.0.0.1"}

    def get(self, key: str, default=None):
        return self._conf.get(key, default)

    def set(self, key: str, value):
        self._conf[key] = value


class SparkSession:
    _active: Optional["SparkSession"] = None

    def __init__(self, master: str = "local[2]"):
        self.conf = _RuntimeConf()
        self.sparkContext = SparkContext()
        SparkContext._active_spark_context = self.sparkContext
        m = re.match(r"local\[(\d+|\*)\]", master or "local[2]")
        self.default_parallelism = (
            os.cpu_count() if m and m.group(1) == "*" else int(m.group(1)) if m else 2
        )

    class _Builder:
        def __init__(self):
            self._master = "local[2]"

        def master(self, m):
            self._master = m
            return self

        def appName(self, _):
            return self

        def config(self, *_, **__):
            return self

        def getOrCreate(self) -> "SparkSession":
            if SparkSession._active is None:
                SparkSession._active = SparkSession(self._master)
            return SparkSession._active

    builder = None  # replaced below (class-level property pattern)

    def createDataFrame(self, data, schema=None) -> DataFrame:
        if hasattr(data, "columns") and hasattr(data, "to_dict"):  # pandas
            cols = {c: list(data[c]) for c in data.columns}
        elif data and isinstance(data[0], dict):
            cols = {k: [row[k] for row in data] for k in data[0]}
        elif data and isinstance(data[0], (tuple, list, Row)):
            if schema is None:
                raise ValueError("schema (column names) required for tuple rows")
            names = schema if isinstance(schema, (list, tuple)) else [
                f.name for f in schema.fields
            ]
            cols = {n: [row[i] for row in data] for i, n in enumerate(names)}
        elif isinstance(data, dict):
            cols = {k: list(v) for k, v in data.items()}
        else:
            raise TypeError(f"cannot build DataFrame from {type(data)}")
        return DataFrame(cols, self, npartitions=self.default_parallelism)

    def stop(self):
        SparkSession._active = None
        SparkContext._active_spark_context = None


class _BuilderDescriptor:
    def __get__(self, obj, objtype=None):
        return SparkSession._Builder()


SparkSession.builder = _BuilderDescriptor()


# ---------------------------------------------------------------------------
# ML: base classes, StopWordsRemover, Pipeline persistence
# ---------------------------------------------------------------------------


class HasInputCol(Params):
    inputCol = Param(Params._dummy(), "inputCol", "input column name",
                     TypeConverters.toString)

    def getInputCol(self):
        return self.getOrDefault(self.inputCol)


class HasLabelCol(Params):
    labelCol = Param(Params._dummy(), "labelCol", "label column name",
                     TypeConverters.toString)

    def getLabelCol(self):
        return self.getOrDefault(self.labelCol)


class HasPredictionCol(Params):
    predictionCol = Param(Params._dummy(), "predictionCol",
                          "prediction column name", TypeConverters.toString)

    def getPredictionCol(self):
        return self.getOrDefault(self.predictionCol)


class Estimator(Params):
    def __init__(self):
        super().__init__()
        self.uid = f"{type(self).__name__}_{uuid.uuid4().hex[:12]}"

    def fit(self, dataset, params: Optional[dict] = None):
        est = self.copy(params) if params else self
        return est._fit(dataset)


class Transformer(Params):
    def __init__(self):
        super().__init__()
        self.uid = f"{type(self).__name__}_{uuid.uuid4().hex[:12]}"

    def transform(self, dataset, params: Optional[dict] = None):
        t = self.copy(params) if params else self
        return t._transform(dataset)


class Model(Transformer):
    pass


class StopWordsRemover(Transformer):
    """The carrier class of the reference's persistence trick
    (reference ``pipeline_util.py:16-31``): a JVM-persistable stage
    whose stopwords list smuggles a dill payload."""

    inputCol = Param(Params._dummy(), "inputCol", "", TypeConverters.toString)
    outputCol = Param(Params._dummy(), "outputCol", "", TypeConverters.toString)
    stopWords = Param(Params._dummy(), "stopWords", "", TypeConverters.toList)

    def __init__(self, inputCol=None, outputCol=None):
        super().__init__()
        self.uid = f"StopWordsRemover_{uuid.uuid4().hex[:12]}"
        self._set(inputCol=inputCol, outputCol=outputCol)
        self._setDefault(stopWords=[])

    def setStopWords(self, words):
        return self._set(stopWords=list(words))

    def getStopWords(self):
        return self.getOrDefault(self.stopWords)

    def _transform(self, dataset):
        return dataset  # carrier-only usage here


_JSON_STAGES = {"StopWordsRemover": StopWordsRemover}


def _stage_to_entry(stage) -> dict:
    """Persist one stage. Pure-Python stages must provide
    ``_to_carrier()`` (the shim analog of pyspark's ``_to_java`` hook,
    reference ``pipeline_util.py:112-130``) to become a carrier."""
    if type(stage).__name__ not in _JSON_STAGES and hasattr(stage, "_to_carrier"):
        stage = stage._to_carrier()
    cls = type(stage).__name__
    if cls not in _JSON_STAGES:
        raise ValueError(
            f"stage {stage!r} is not JVM-persistable and has no _to_carrier "
            "hook (see sparktorch_tpu.spark.pipeline_util)"
        )
    return {"className": cls, "uid": stage.uid,
            "paramMap": stage.extractParamMap()}


def _entry_to_stage(entry: dict):
    stage = _JSON_STAGES[entry["className"]].__new__(
        _JSON_STAGES[entry["className"]]
    )
    Params.__init__(stage)
    stage.uid = entry["uid"]
    stage._set(**entry["paramMap"])
    return stage


class _PipelineWriter:
    def __init__(self, target):
        self._target = target
        self._overwrite = False

    def overwrite(self):
        self._overwrite = True
        return self

    def save(self, path: str):
        if os.path.exists(path) and not self._overwrite:
            raise FileExistsError(path)
        os.makedirs(path, exist_ok=True)
        meta = {
            "class": type(self._target).__name__,
            "stages": [_stage_to_entry(s) for s in self._target.stages],
        }
        with open(os.path.join(path, "metadata.json"), "w") as f:
            json.dump(meta, f)  # lint-obs: ok (JVM-parity metadata)


class Pipeline(Estimator):
    def __init__(self, stages: Optional[list] = None):
        super().__init__()
        self.stages = list(stages or [])

    def getStages(self):
        return self.stages

    def setStages(self, stages):
        self.stages = list(stages)
        return self

    def _fit(self, dataset):
        # pyspark semantics: transform feeds only LATER estimators, so
        # stages at/after the last estimator are not transformed during
        # fit (no wasted inference pass on the training set).
        est_idx = [i for i, s in enumerate(self.stages) if hasattr(s, "fit")]
        last_est = est_idx[-1] if est_idx else -1
        fitted = []
        df = dataset
        for i, stage in enumerate(self.stages):
            model = stage.fit(df) if hasattr(stage, "fit") else stage
            fitted.append(model)
            if i < last_est and hasattr(model, "transform"):
                df = model.transform(df)
        return PipelineModel(fitted)

    def write(self) -> _PipelineWriter:
        return _PipelineWriter(self)

    def save(self, path: str):
        self.write().save(path)

    @classmethod
    def load(cls, path: str) -> "Pipeline":
        return _load_pipeline(path, cls)


class PipelineModel(Model):
    def __init__(self, stages: list):
        super().__init__()
        self.stages = list(stages)

    def _transform(self, dataset):
        df = dataset
        for stage in self.stages:
            df = stage.transform(df)
        return df

    def write(self) -> _PipelineWriter:
        return _PipelineWriter(self)

    def save(self, path: str):
        self.write().save(path)

    @classmethod
    def load(cls, path: str) -> "PipelineModel":
        return _load_pipeline(path, cls)


def _load_pipeline(path: str, cls):
    with open(os.path.join(path, "metadata.json")) as f:
        meta = json.load(f)
    stages = [_entry_to_stage(e) for e in meta["stages"]]
    if cls is Pipeline:
        return Pipeline(stages)
    return PipelineModel(stages)


# ---------------------------------------------------------------------------
# install(): register as the pyspark the adapter imports
# ---------------------------------------------------------------------------


def install(force: bool = False) -> bool:
    """Register this runtime under the ``pyspark`` module names.

    Returns True if installed, False if real pyspark is present (in
    which case nothing is touched — the adapter uses the real one).
    """
    if not force:
        try:
            import pyspark  # noqa: F401

            if not getattr(pyspark, "__localspark__", False):
                return False
            return True  # our own earlier install
        except ImportError:
            pass

    import types

    def module(name: str, **attrs) -> types.ModuleType:
        mod = sys.modules.get(name)
        if mod is None:
            mod = types.ModuleType(name)
            sys.modules[name] = mod
        for k, v in attrs.items():
            setattr(mod, k, v)
        return mod

    pyspark = module(
        "pyspark",
        __localspark__=True,
        keyword_only=keyword_only,
        BarrierTaskContext=BarrierTaskContext,
        SparkContext=SparkContext,
    )
    pyspark.sql = module(
        "pyspark.sql", SparkSession=SparkSession, DataFrame=DataFrame, Row=Row
    )
    pyspark.sql.functions = module("pyspark.sql.functions", pandas_udf=pandas_udf)
    pyspark.sql.types = module(
        "pyspark.sql.types",
        ArrayType=ArrayType, DoubleType=DoubleType, FloatType=FloatType,
        StructType=StructType, StructField=StructField,
    )
    ml = module(
        "pyspark.ml", Pipeline=Pipeline, PipelineModel=PipelineModel,
        Estimator=Estimator, Transformer=Transformer, Model=Model,
    )
    ml.base = module(
        "pyspark.ml.base", Estimator=Estimator, Transformer=Transformer,
        Model=Model,
    )
    ml.param = module(
        "pyspark.ml.param", Param=Param, Params=Params,
        TypeConverters=TypeConverters,
    )
    ml.param.shared = module(
        "pyspark.ml.param.shared",
        HasInputCol=HasInputCol, HasLabelCol=HasLabelCol,
        HasPredictionCol=HasPredictionCol,
    )
    ml.feature = module("pyspark.ml.feature", StopWordsRemover=StopWordsRemover)
    ml.linalg = module(
        "pyspark.ml.linalg", DenseVector=DenseVector, Vectors=Vectors,
        VectorUDT=VectorUDT,
    )
    ml.functions = module("pyspark.ml.functions", vector_to_array=vector_to_array)
    ml.util = module(
        "pyspark.ml.util",
        JavaMLWriter=JavaMLWriter, JavaMLReader=JavaMLReader,
        MLReadable=MLReadable, MLWritable=MLWritable,
        Identifiable=Identifiable,
    )
    ml.wrapper = module("pyspark.ml.wrapper", JavaParams=JavaParams)
    pyspark.context = module("pyspark.context", SparkContext=SparkContext)
    return True
