"""Executor-process entry for the localspark runtime.

Spawned by ``localsession.RDD._run_executors`` — one process per
partition, the analog of Spark's forked Python workers. The bootstrap
order is load-bearing: the CPU platform must be pinned *before* any
code (including dill unpickling, which imports the framework and
therefore jax) can initialize a backend, because on this machine a
TPU plugin grabs the chip exclusively and sitecustomize re-registers
it over the env var.
"""

import sys


def main(payload_path: str, result_path: str) -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")

    from sparktorch_tpu.spark import localsession

    localsession.install()

    import dill
    import json

    with open(payload_path, "rb") as f:
        header = json.loads(f.readline())
        for p in header["sys_path"]:
            if p not in sys.path:
                sys.path.append(p)
        payload = dill.load(f)

    if payload["barrier"]:
        localsession.BarrierTaskContext._current = localsession.BarrierTaskContext(
            payload["partition_id"], payload["world"]
        )

    out = payload["fn"](iter(payload["rows"]))
    with open(result_path, "wb") as f:
        dill.dump(list(out), f)


if __name__ == "__main__":
    main(sys.argv[1], sys.argv[2])
