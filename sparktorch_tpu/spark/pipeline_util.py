"""Decode/encode Spark-ML pipelines that carry Python stages.

Reference mechanism (``sparktorch/pipeline_util.py``): PySpark cannot
persist pure-Python Transformers, so the reference dill-dumps the
Python object, zlib-compresses it, renders the bytes as a
comma-joined decimal string and stores it as the stopwords list of a
``StopWordsRemover`` (the JVM "carrier class"), tagged with a magic
GUID (:16-31, :112-130); ``unwrap`` walks loaded stages and
re-hydrates carriers, recursing into nested pipelines (:49-77).

This adapter interoperates with that on-disk format: pipelines saved
by the reference (or by this adapter) load back into live Python
objects. The GUID below matches the reference's tag so *existing*
saved pipelines remain readable — it is a file-format constant, like
a magic number.
"""

from __future__ import annotations

import zlib
from typing import Any, List

import dill

try:
    from pyspark.ml import Pipeline as SparkPipeline
    from pyspark.ml import PipelineModel as SparkPipelineModel
    from pyspark.ml.feature import StopWordsRemover
except ImportError as _e:  # pragma: no cover - exercised only w/ pyspark
    raise ImportError(
        "sparktorch_tpu.spark requires pyspark; install it or use the "
        "native sparktorch_tpu.ml.Pipeline persistence instead"
    ) from _e

# File-format constant: the magic id tagging carrier stages. Matches
# the reference's on-disk tag (pipeline_util.py:27) so pipelines saved
# by the reference remain readable.
CARRIER_GUID = "4c1740b00d3c4ff6806a1402321572cb"


def encode_python_stage(obj: Any, uid: str) -> StopWordsRemover:
    """Pack a Python stage into a JVM-persistable carrier stage."""
    payload = zlib.compress(dill.dumps(obj))
    # Trailing comma matters: the reference's reader does
    # ``split(',')[0:-1]`` (pipeline_util.py:35), so a string without
    # it would lose its last byte there.
    as_decimal = "".join(f"{b}," for b in payload)
    carrier = StopWordsRemover(inputCol=uid, outputCol=uid + "_out")
    carrier.setStopWords([as_decimal, CARRIER_GUID])
    return carrier


def decode_carrier_stage(stage) -> Any:
    """Carrier stage -> live Python object."""
    words: List[str] = stage.getStopWords()
    payload = bytes(int(tok) for tok in words[0].split(",") if tok)
    return dill.loads(zlib.decompress(payload))


def is_carrier(stage) -> bool:
    if not isinstance(stage, StopWordsRemover):
        return False
    words = stage.getStopWords()
    return bool(words) and words[-1] == CARRIER_GUID


class PythonStagePersistence:
    """Mixin that lets a pure-Python pyspark stage survive
    ``Pipeline.write().save(path)`` / ``PipelineModel.load(path)``.

    Parity: the reference's ``PysparkReaderWriter`` (reference
    ``pipeline_util.py:80-130``) — when the surrounding pipeline is
    persisted, the stage converts itself into the JVM-persistable
    carrier (a ``StopWordsRemover`` whose stopwords smuggle the dill
    payload, tagged with the magic GUID); loading + ``unwrap`` (below)
    restores the live Python object.

    Two hooks cover both runtimes: real pyspark's ``JavaMLWriter``
    calls ``_to_java`` (we build a real StopWordsRemover and delegate
    to its own ``_to_java``); the localspark runtime's pipeline writer
    calls ``_to_carrier``.
    """

    def _to_carrier(self):
        return encode_python_stage(self, getattr(self, "uid", "pystage"))

    def _to_java(self):  # pragma: no cover - needs a JVM gateway
        return self._to_carrier()._to_java()

    @classmethod
    def _from_java(cls, java_stage):  # pragma: no cover - needs a JVM
        py_carrier = StopWordsRemover()
        py_carrier._java_obj = java_stage
        py_carrier._transfer_params_from_java()
        return decode_carrier_stage(py_carrier)


def unwrap_spark_pipeline(pipeline):
    """Re-hydrate carrier stages in a loaded Spark pipeline.

    Parity: ``PysparkPipelineWrapper.unwrap`` (pipeline_util.py:49-77),
    including recursion into nested pipelines.
    """
    if isinstance(pipeline, (SparkPipeline, SparkPipelineModel)):
        stages = pipeline.getStages() if hasattr(pipeline, "getStages") else pipeline.stages
        new_stages = []
        for stage in stages:
            if is_carrier(stage):
                new_stages.append(decode_carrier_stage(stage))
            elif isinstance(stage, (SparkPipeline, SparkPipelineModel)):
                new_stages.append(unwrap_spark_pipeline(stage))
            else:
                new_stages.append(stage)
        if hasattr(pipeline, "setStages"):
            pipeline.setStages(new_stages)
        else:
            pipeline.stages = new_stages
    return pipeline


class PysparkPipelineWrapper:
    """Reference-named entry point (``pipeline_util.py:49-77``):
    ``PysparkPipelineWrapper.unwrap(PipelineModel.load(path))``."""

    @staticmethod
    def unwrap(pipeline):
        return unwrap_spark_pipeline(pipeline)
