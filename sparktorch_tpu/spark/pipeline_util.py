"""Decode/encode Spark-ML pipelines that carry Python stages.

Reference mechanism (``sparktorch/pipeline_util.py``): PySpark cannot
persist pure-Python Transformers, so the reference dill-dumps the
Python object, zlib-compresses it, renders the bytes as a
comma-joined decimal string and stores it as the stopwords list of a
``StopWordsRemover`` (the JVM "carrier class"), tagged with a magic
GUID (:16-31, :112-130); ``unwrap`` walks loaded stages and
re-hydrates carriers, recursing into nested pipelines (:49-77).

This adapter interoperates with that on-disk format: pipelines saved
by the reference (or by this adapter) load back into live Python
objects. The GUID below matches the reference's tag so *existing*
saved pipelines remain readable — it is a file-format constant, like
a magic number.
"""

from __future__ import annotations

import zlib
from typing import Any, List

import dill

try:
    from pyspark.context import SparkContext
    from pyspark.ml import Pipeline as SparkPipeline
    from pyspark.ml import PipelineModel as SparkPipelineModel
    from pyspark.ml.feature import StopWordsRemover
    from pyspark.ml.util import JavaMLReader, JavaMLWriter
    from pyspark.ml.wrapper import JavaParams
except ImportError as _e:  # pragma: no cover - exercised only w/ pyspark
    raise ImportError(
        "sparktorch_tpu.spark requires pyspark; install it or use the "
        "native sparktorch_tpu.ml.Pipeline persistence instead"
    ) from _e

# File-format constant: the magic id tagging carrier stages. Matches
# the reference's on-disk tag (pipeline_util.py:27) so pipelines saved
# by the reference remain readable.
CARRIER_GUID = "4c1740b00d3c4ff6806a1402321572cb"


def _payload_strings(obj: Any) -> List[str]:
    """dill -> zlib -> decimal-rendered bytes, GUID-tagged — the
    2-element stopwords list that IS the carrier file format."""
    payload = zlib.compress(dill.dumps(obj))
    # Trailing comma matters: the reference's reader does
    # ``split(',')[0:-1]`` (pipeline_util.py:35), so a string without
    # it would lose its last byte there.
    return ["".join(f"{b}," for b in payload), CARRIER_GUID]


def encode_python_stage(obj: Any, uid: str) -> StopWordsRemover:
    """Pack a Python stage into a JVM-persistable carrier stage."""
    carrier = StopWordsRemover(inputCol=uid, outputCol=uid + "_out")
    carrier.setStopWords(_payload_strings(obj))
    return carrier


def decode_carrier_stage(stage) -> Any:
    """Carrier stage -> live Python object."""
    words: List[str] = stage.getStopWords()
    payload = bytes(int(tok) for tok in words[0].split(",") if tok)
    return dill.loads(zlib.decompress(payload))


def is_carrier(stage) -> bool:
    if not isinstance(stage, StopWordsRemover):
        return False
    words = stage.getStopWords()
    return bool(words) and words[-1] == CARRIER_GUID


class PythonStagePersistence:
    """Mixin that lets a pure-Python pyspark stage (estimator, model,
    or transformer) be saved and loaded — directly via
    ``stage.write().save(path)`` / ``Cls.load(path)``, or inside a
    surrounding ``Pipeline``/``PipelineModel``.

    Parity: the reference's ``PysparkReaderWriter`` (reference
    ``pipeline_util.py:80-130``), mixed into BOTH the estimator and
    the model (reference ``torch_distributed.py:58,130-138``):

    - ``write()`` returns the runtime's ``JavaMLWriter`` over this
      instance, whose save path calls ``_to_java`` (reference :88-90);
    - ``read()``/``load()`` go through ``JavaMLReader`` on the carrier
      class and re-hydrate with ``_from_java`` (reference :92-101);
    - ``_to_java`` performs the gateway-side carrier construction
      itself — dill dump, zlib, decimal string array through
      ``sc._gateway.new_array``, ``JavaParams._new_java_obj`` of the
      carrier class (reference :112-130). Under real pyspark these
      calls cross the Py4J bridge into the JVM; under localspark they
      hit the protocol-faithful local gateway — the same code path
      either way.

    ``_to_carrier`` additionally serves the localspark pipeline
    writer, which persists carrier stages as JSON param maps.
    """

    def write(self) -> "JavaMLWriter":
        return JavaMLWriter(self)

    @classmethod
    def read(cls) -> "JavaMLReader":
        return JavaMLReader(StopWordsRemover)

    @classmethod
    def load(cls, path: str):
        obj = cls._from_java(cls.read().load(path))
        # The carrier format has no class discriminator; catch a
        # wrong-kind load (model path through SparkTorch.load, etc.)
        # here rather than as a far-away AttributeError.
        if cls is not PythonStagePersistence and not isinstance(obj, cls):
            raise TypeError(
                f"{path} holds a {type(obj).__name__}, not a {cls.__name__}"
            )
        return obj

    def _to_carrier(self) -> StopWordsRemover:
        return encode_python_stage(self, getattr(self, "uid", "pystage"))

    def _to_java(self):
        pylist = _payload_strings(self)
        sc = SparkContext._active_spark_context
        if sc is None:
            raise RuntimeError(
                "persistence requires an active SparkSession (the "
                "gateway lives on SparkContext._active_spark_context)"
            )
        java_class = sc._gateway.jvm.java.lang.String
        java_array = sc._gateway.new_array(java_class, len(pylist))
        java_array[0:2] = pylist[0:2]
        java_obj = JavaParams._new_java_obj(
            "org.apache.spark.ml.feature.StopWordsRemover",
            getattr(self, "uid", "pystage"),
        )
        java_obj.setStopWords(java_array)
        return java_obj

    @classmethod
    def _from_java(cls, java_stage):
        """Carrier (JVM object via Py4J, or any object exposing
        ``getStopWords``) -> live Python instance."""
        words = list(java_stage.getStopWords())
        if not words or words[-1] != CARRIER_GUID:
            raise ValueError("stage is not a sparktorch carrier")
        return decode_carrier_stage(java_stage)


def unwrap_spark_pipeline(pipeline):
    """Re-hydrate carrier stages in a loaded Spark pipeline.

    Parity: ``PysparkPipelineWrapper.unwrap`` (pipeline_util.py:49-77),
    including recursion into nested pipelines.
    """
    if isinstance(pipeline, (SparkPipeline, SparkPipelineModel)):
        stages = pipeline.getStages() if hasattr(pipeline, "getStages") else pipeline.stages
        new_stages = []
        for stage in stages:
            if is_carrier(stage):
                new_stages.append(decode_carrier_stage(stage))
            elif isinstance(stage, (SparkPipeline, SparkPipelineModel)):
                new_stages.append(unwrap_spark_pipeline(stage))
            else:
                new_stages.append(stage)
        if hasattr(pipeline, "setStages"):
            pipeline.setStages(new_stages)
        else:
            pipeline.stages = new_stages
    return pipeline


class PysparkPipelineWrapper:
    """Reference-named entry point (``pipeline_util.py:49-77``):
    ``PysparkPipelineWrapper.unwrap(PipelineModel.load(path))``."""

    @staticmethod
    def unwrap(pipeline):
        return unwrap_spark_pipeline(pipeline)
