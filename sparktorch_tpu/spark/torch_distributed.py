"""PySpark Estimator/Model adapter over the TPU trainers.

The deployment-facing analog of the reference's
``sparktorch/torch_distributed.py``: a real ``pyspark.ml`` Estimator
with the same Param surface, fitting models on TPU hardware. Two
deploy modes:

- ``deployMode='driver'`` (default): executors only *produce data*
  (their partitions stream to the driver), the driver runs the SPMD
  trainer over its attached TPU slice. This inverts the reference's
  topology (training on executors) because on TPU pods the
  accelerator set is attached to dedicated hosts, not to Spark
  executors; it removes the reference's phantom-rank and
  hardcoded-port machinery outright.
- ``deployMode='barrier'``: the reference's topology, TPU-native —
  one Spark **barrier task per TPU host** (``rdd.barrier()``; the
  reference builds a barrier RDD at ``distributed.py:39-43``). Task
  index = process rank; the driver runs the native C++ gang
  coordinator; each task calls
  :func:`sparktorch_tpu.parallel.launch.bringup_multihost`, which
  rendezvouses and runs ``jax.distributed.initialize`` so the pod
  forms one global mesh; every host feeds its partition into the
  shared SPMD step (weight-0 padding absorbs skew — no phantom
  ranks). Requires executors co-located with the TPU hosts.

Inference (`SparkTorchModel._transform`) is an Arrow-batched pandas
UDF over a broadcast model bundle running the compiled chunked
forward — versus the reference's batch-1 row UDF
(``torch_distributed.py:106-120``).

This module imports pyspark at import time and is exercised only in
Spark deployments (pyspark is not in this repo's test image).
"""

from __future__ import annotations

import numpy as np

try:
    from pyspark import keyword_only
    from pyspark.ml.base import Estimator, Model
    from pyspark.ml.param import Param, Params, TypeConverters
    from pyspark.ml.param.shared import HasInputCol, HasLabelCol, HasPredictionCol
    from pyspark.ml.util import MLReadable, MLWritable
    from pyspark.sql.functions import pandas_udf
    from pyspark.sql.types import ArrayType, DoubleType
except ImportError as _e:  # pragma: no cover
    raise ImportError(
        "sparktorch_tpu.spark requires pyspark; use sparktorch_tpu.ml for "
        "the JVM-free surface"
    ) from _e


from sparktorch_tpu.ml.estimator import _decode_bundle, _encode_bundle
from sparktorch_tpu.spark.pipeline_util import PythonStagePersistence
from sparktorch_tpu.utils.serde import deserialize_model


def _labels_to_f32(values, label_col) -> np.ndarray:
    try:
        return np.asarray(values, dtype=np.float32)
    except (TypeError, ValueError) as e:
        raise ValueError(
            f"labelCol {label_col!r} must be numeric; index string "
            "labels first (e.g. StringIndexer)"
        ) from e


def _rows_to_x(rows) -> np.ndarray:
    """Stack row features (DenseVector or array-like) into a float32
    matrix — the vectorized analog of the reference's per-row
    ``row[input_col].toArray()`` (torch_distributed.py:43-55)."""
    return np.stack([
        np.asarray(r[0], dtype=np.float32)
        if not hasattr(r[0], "toArray")
        else r[0].toArray().astype(np.float32)
        for r in rows
    ])


class _SparkTorchParams(HasInputCol, HasLabelCol, HasPredictionCol):
    """The reference's 14 declared Params (torch_distributed.py:141-154)
    plus deployMode."""

    torchObj = Param(Params._dummy(), "torchObj", "serialized model spec",
                     typeConverter=TypeConverters.toString)
    mode = Param(Params._dummy(), "mode", "synchronous | hogwild",
                 typeConverter=TypeConverters.toString)
    device = Param(Params._dummy(), "device", "parity no-op (mesh decides)",
                   typeConverter=TypeConverters.toString)
    iters = Param(Params._dummy(), "iters", "", typeConverter=TypeConverters.toInt)
    partitions = Param(Params._dummy(), "partitions", "",
                       typeConverter=TypeConverters.toInt)
    verbose = Param(Params._dummy(), "verbose", "", typeConverter=TypeConverters.toInt)
    acquireLock = Param(Params._dummy(), "acquireLock", "",
                        typeConverter=TypeConverters.toBoolean)
    partitionShuffles = Param(Params._dummy(), "partitionShuffles", "",
                              typeConverter=TypeConverters.toInt)
    port = Param(Params._dummy(), "port", "", typeConverter=TypeConverters.toInt)
    useBarrier = Param(Params._dummy(), "useBarrier", "",
                       typeConverter=TypeConverters.toBoolean)
    useVectorOut = Param(Params._dummy(), "useVectorOut", "",
                         typeConverter=TypeConverters.toBoolean)
    earlyStopPatience = Param(Params._dummy(), "earlyStopPatience", "",
                              typeConverter=TypeConverters.toInt)
    miniBatch = Param(Params._dummy(), "miniBatch", "",
                      typeConverter=TypeConverters.toInt)
    validationPct = Param(Params._dummy(), "validationPct", "",
                          typeConverter=TypeConverters.toFloat)
    deployMode = Param(Params._dummy(), "deployMode", "driver | barrier",
                       typeConverter=TypeConverters.toString)
    pushEvery = Param(Params._dummy(), "pushEvery",
                      "hogwild: fuse k grad steps into one compiled window "
                      "per push (k-fold fewer wire round-trips; the window "
                      "is the staleness unit)",
                      typeConverter=TypeConverters.toInt)
    compress = Param(Params._dummy(), "compress",
                     "hogwild: bf16-compress gradient pushes on the wire",
                     typeConverter=TypeConverters.toBoolean)
    wire = Param(Params._dummy(), "wire",
                 "hogwild HTTP wire format: 'binary' (framed zero-copy "
                 "tensor protocol, keep-alive, 304 pulls) or 'dill' "
                 "(reference-parity pickle wire for mixed-version gangs)",
                 typeConverter=TypeConverters.toString)
    supervise = Param(Params._dummy(), "supervise",
                      "fault tolerance: restart a failed barrier stage "
                      "under the ft policy, resuming from the latest "
                      "checkpoint; the gang coordinator opens a rejoin "
                      "grace window so restarted ranks re-register on a "
                      "fresh generation",
                      typeConverter=TypeConverters.toBoolean)
    ftMaxRestarts = Param(Params._dummy(), "ftMaxRestarts",
                          "fault tolerance: restart budget for the "
                          "supervised barrier stage",
                          typeConverter=TypeConverters.toInt)
    checkpointDir = Param(Params._dummy(), "checkpointDir",
                          "step-indexed checkpoint directory (shared FS "
                          "across TPU hosts); supervised restarts resume "
                          "from the latest finalized snapshot",
                          typeConverter=TypeConverters.toString)
    checkpointEvery = Param(Params._dummy(), "checkpointEvery",
                            "save a snapshot every N steps (0 disables)",
                            typeConverter=TypeConverters.toInt)


class SparkTorch(Estimator, _SparkTorchParams, PythonStagePersistence,
                 MLReadable, MLWritable):
    """Persistence is mixed into the ESTIMATOR too (reference
    ``torch_distributed.py:130-138``): an *unfitted* Pipeline holding
    a SparkTorch stage saves/loads, and the stage saves directly via
    ``write()``/``load()``. ``MLReadable``/``MLWritable`` mark the
    stage persistable to pyspark's Pipeline writer (the reference
    mixes them the same way); ``PythonStagePersistence`` precedes them
    in the MRO so its concrete ``write``/``read``/``load`` win."""

    @keyword_only
    def __init__(self, inputCol=None, labelCol=None, predictionCol=None,
                 torchObj=None, iters=None, partitions=None, verbose=None,
                 mode=None, device=None, acquireLock=None,
                 partitionShuffles=None, port=None, useBarrier=None,
                 useVectorOut=None, earlyStopPatience=None, miniBatch=None,
                 validationPct=None, deployMode=None, pushEvery=None,
                 compress=None, wire=None, supervise=None,
                 ftMaxRestarts=None, checkpointDir=None,
                 checkpointEvery=None):
        super().__init__()
        self._setDefault(
            predictionCol="predictions", mode="synchronous", device="tpu",
            iters=10, verbose=0, acquireLock=True, partitionShuffles=1,
            port=3000, useBarrier=True, useVectorOut=False,
            earlyStopPatience=-1, miniBatch=-1, validationPct=0.0,
            deployMode="driver", pushEvery=1, compress=True, wire="binary",
            supervise=False, ftMaxRestarts=2, checkpointEvery=0,
        )
        self._set(**self._input_kwargs)

    @keyword_only
    def setParams(self, **kwargs):
        return self._set(**self._input_kwargs)

    # -- data movement -----------------------------------------------------

    def _collect_xy(self, dataset):
        """Executors -> driver column stream (deployMode='driver')."""
        inp = self.getOrDefault(self.inputCol)
        label = (self.getOrDefault(self.labelCol)
                 if self.isDefined(self.labelCol) else None)
        cols = [inp] + ([label] if label else [])
        rows = dataset.select(*cols).collect()
        x = _rows_to_x(rows)
        y = _labels_to_f32([r[1] for r in rows], label) if label else None
        return x, y

    # -- fit ---------------------------------------------------------------

    def _fit(self, dataset):
        if self.getOrDefault(self.deployMode) == "barrier":
            if self.getOrDefault(self.mode) in ("hogwild", "async"):
                result = self._fit_hogwild_executors(dataset)
            else:
                result = self._fit_barrier(dataset)
        else:
            result = self._fit_driver(dataset)
        return SparkTorchModel(
            inputCol=self.getOrDefault(self.inputCol),
            predictionCol=self.getOrDefault(self.predictionCol),
            modStr=result,
            useVectorOut=self.getOrDefault(self.useVectorOut),
        )

    def _fit_driver(self, dataset) -> str:
        x, y = self._collect_xy(dataset)
        spec = deserialize_model(self.getOrDefault(self.torchObj))
        mini_batch = self.getOrDefault(self.miniBatch)
        mini_batch = None if mini_batch <= 0 else mini_batch
        mode = self.getOrDefault(self.mode)
        if mode in ("hogwild", "async"):
            from sparktorch_tpu.train.hogwild import train_async

            result = train_async(
                spec, x, labels=y,
                iters=self.getOrDefault(self.iters),
                partition_shuffles=self.getOrDefault(self.partitionShuffles),
                verbose=self.getOrDefault(self.verbose),
                mini_batch=mini_batch,
                validation_pct=self.getOrDefault(self.validationPct),
                early_stop_patience=self.getOrDefault(self.earlyStopPatience),
                acquire_lock=self.getOrDefault(self.acquireLock),
                port=self.getOrDefault(self.port),
                partitions=self.getOrDefault(self.partitions)
                if self.isDefined(self.partitions) else -1,
                push_every=self.getOrDefault(self.pushEvery),
                compress=self.getOrDefault(self.compress),
            )
        else:
            from sparktorch_tpu.train.sync import train_distributed

            result = train_distributed(
                spec, x, labels=y,
                iters=self.getOrDefault(self.iters),
                partition_shuffles=self.getOrDefault(self.partitionShuffles),
                verbose=self.getOrDefault(self.verbose),
                mini_batch=mini_batch,
                validation_pct=self.getOrDefault(self.validationPct),
                early_stop_patience=self.getOrDefault(self.earlyStopPatience),
            )
        return _encode_bundle(result.spec, result.params, result.model_state)

    def _fit_hogwild_executors(self, dataset) -> str:
        """The reference's hogwild topology, executor-side: the DRIVER
        hosts the parameter server (``ParamServerHttp``), executor
        tasks run the async worker loop over the HTTP wire —
        pull/grad/push per iteration with version-tagged pulls
        (reference ``hogwild.py:65-142`` + ``torch_distributed.py:
        310-334``).
        """
        inp = self.getOrDefault(self.inputCol)
        label = (self.getOrDefault(self.labelCol)
                 if self.isDefined(self.labelCol) else None)
        torch_obj = self.getOrDefault(self.torchObj)
        iters = self.getOrDefault(self.iters)
        mini_batch = self.getOrDefault(self.miniBatch)
        mini_batch = None if mini_batch <= 0 else mini_batch
        shuffles = max(1, self.getOrDefault(self.partitionShuffles))
        verbose = self.getOrDefault(self.verbose)
        patience = self.getOrDefault(self.earlyStopPatience)
        validation_pct = self.getOrDefault(self.validationPct)
        # Explicitly-set port is honored (reference default 3000);
        # otherwise ephemeral, so concurrent fits never collide.
        port = self.getOrDefault(self.port) if self.isSet(self.port) else 0
        lock = self.getOrDefault(self.acquireLock)
        push_every = max(1, self.getOrDefault(self.pushEvery))
        compress = self.getOrDefault(self.compress)
        wire_fmt = self.getOrDefault(self.wire)
        if wire_fmt not in ("binary", "dill"):
            # Fail fast like train_async(wire=...): a typo must not
            # silently run the wrong wire in a parity experiment.
            raise ValueError(
                f"unknown wire {wire_fmt!r}; use 'binary' or 'dill'"
            )
        spark = dataset.sparkSession
        driver_host = spark.conf.get("spark.driver.host", "127.0.0.1")
        n_parts = (self.getOrDefault(self.partitions)
                   if self.isDefined(self.partitions)
                   else dataset.rdd.getNumPartitions())
        base = dataset.select(*([inp] + ([label] if label else [])))

        from sparktorch_tpu.serve.param_server import (
            ParameterServer,
            ParamServerHttp,
        )

        spec = deserialize_model(torch_obj)
        if spec.input_shape is None:
            first = dataset.select(inp).take(1)
            if not first:
                raise ValueError("cannot infer input shape from empty data")
            v = first[0][0]
            spec.input_shape = tuple(
                np.asarray(v.toArray() if hasattr(v, "toArray") else v).shape
            )

        server = ParameterServer(
            spec, window_len=n_parts, early_stop_patience=patience,
            acquire_lock=lock, seed=0,
        )
        # Bind all interfaces (executors are remote); workers reach the
        # driver through spark.driver.host.
        http = ParamServerHttp(server, host="0.0.0.0", port=port).start()
        url = f"http://{driver_host}:{http.port}"
        early_stop = patience is not None and patience > 0

        def make_run_worker(round_seed: int):
            def run_worker(iterator):
                rows = list(iterator)
                if not rows:
                    return  # hogwild has no collectives: empty task exits
                import os as _os

                import jax as _jax

                from sparktorch_tpu.train.hogwild import (
                    HttpTransport,
                    _worker_loop,
                    make_eval_loss,
                    make_grad_step,
                    make_grad_windows,
                )
                from sparktorch_tpu.utils.data import handle_features
                from sparktorch_tpu.utils.serde import (
                    deserialize_model as _deserialize,
                )

                if wire_fmt == "dill":
                    transport = HttpTransport(url, compress=compress)
                else:
                    from sparktorch_tpu.net.transport import BinaryTransport

                    transport = BinaryTransport(
                        url, quant="bf16" if compress else None
                    )
                assert transport.alive()  # GET / liveness (hogwild.py:60-62)
                w_spec = _deserialize(torch_obj)
                x = _rows_to_x(rows)
                if w_spec.input_shape is None:
                    w_spec.input_shape = tuple(x.shape[1:])
                y = _labels_to_f32([r[1] for r in rows], label) if label else x
                if mini_batch and mini_batch > 0:
                    # Block minibatch sampling (sample_minibatch)
                    # requires random resident order; a label-sorted
                    # partition would otherwise feed single-class
                    # blocks all run. handle_features only permutes
                    # when validation_pct > 0, so shuffle here.
                    perm = np.random.default_rng(round_seed).permutation(
                        x.shape[0]
                    )
                    x, y = x[perm], y[perm]
                # Per-partition validation split, like the reference's
                # executor-side handle_features (util.py:57-100).
                shard, val_shard = handle_features(
                    x, y, validation_pct, seed=round_seed
                )
                module = w_spec.make_module()
                grad_step = make_grad_step(module.apply, w_spec.loss_fn(),
                                           mini_batch=mini_batch)
                # pushEvery=k: one compiled k-step window per wire
                # round-trip — the amortization built for exactly this
                # deployment (executors over real HTTP).
                grad_windows = make_grad_windows(
                    module.apply, w_spec.loss_fn(), mini_batch, push_every,
                    iters,
                )
                eval_loss = (
                    make_eval_loss(module.apply, w_spec.loss_fn())
                    if val_shard is not None else None
                )
                variables = dict(w_spec.init_params(_jax.random.key(0)))
                variables.pop("params", None)
                records, errors = [], []
                _worker_loop(
                    _os.getpid() % 100000, _jax.devices()[0], transport,
                    grad_step, variables, shard,
                    _jax.device_put(val_shard, _jax.devices()[0])
                    if val_shard is not None else None,
                    iters, verbose, early_stop, round_seed,
                    records, errors, push_every=push_every,
                    eval_loss=eval_loss, grad_windows=grad_windows,
                )
                if errors:
                    raise errors[0]
                yield {
                    "worker": _os.getpid(),
                    "losses": [r["loss"] for r in records],
                    "versions": [r["version"] for r in records],
                }

            return run_worker

        try:
            summaries = []
            for round_idx in range(shuffles):  # hogwild.py:161-177 parity
                # A fresh repartition per round moves rows between
                # partitions on a real cluster's shuffle service (the
                # reference's "partition shuffles"); the per-round seed
                # additionally re-randomizes every worker's minibatch
                # stream, which is the shuffle's training-dynamics
                # effect in runtimes (like localspark) whose
                # repartition is only a partition-count hint.
                rdd = base.rdd.repartition(n_parts)
                if self.getOrDefault(self.useBarrier):
                    rdd = rdd.barrier()  # torch_distributed.py:312-313
                summaries.extend(
                    rdd.mapPartitions(
                        make_run_worker(round_idx * 100003)
                    ).collect()
                )
                if server.should_stop:
                    break
            # Introspection hooks for callers/tests (per-worker loss and
            # observed-version traces; server-side applied-push count —
            # with pushEvery=k this is ~iters/k per worker, the proof
            # the wire carried window-sized pushes).
            self._last_hogwild_summaries = summaries
            self._last_hogwild_applied = server.applied_updates
            params, model_state = server.final_state()
            import jax as _jax

            params = _jax.device_get(params)
            model_state = _jax.device_get(model_state)
            return _encode_bundle(server.spec, params, model_state)
        finally:
            # Stop server even on failure (hogwild.py:184-186 parity).
            http.stop()
            server.stop()

    def _fit_barrier(self, dataset) -> str:
        """One barrier task per TPU host; rank = barrier partition id.

        Each task joins the gang (coordinator runs on the DRIVER),
        initializes the pod-wide PJRT runtime, and contributes its
        partition to the GLOBAL batch via
        ``train_distributed_multihost`` (which allgathers row counts,
        pads skewed/empty partitions with weight-0 rows, and builds
        the globally-sharded arrays with
        ``jax.make_array_from_process_local_data``).
        """
        inp = self.getOrDefault(self.inputCol)
        label = (self.getOrDefault(self.labelCol)
                 if self.isDefined(self.labelCol) else None)
        torch_obj = self.getOrDefault(self.torchObj)
        iters = self.getOrDefault(self.iters)
        mini_batch = self.getOrDefault(self.miniBatch)
        mini_batch = None if mini_batch <= 0 else mini_batch
        shuffles = self.getOrDefault(self.partitionShuffles)
        verbose = self.getOrDefault(self.verbose)
        patience = self.getOrDefault(self.earlyStopPatience)
        supervise = self.getOrDefault(self.supervise)
        ckpt_dir = (self.getOrDefault(self.checkpointDir)
                    if self.isDefined(self.checkpointDir) else None)
        ckpt_every = self.getOrDefault(self.checkpointEvery)
        spark = dataset.sparkSession
        gang_host = spark.conf.get("spark.driver.host", "127.0.0.1")
        n_hosts = (self.getOrDefault(self.partitions)
                   if self.isDefined(self.partitions)
                   else dataset.rdd.getNumPartitions())
        rdd = dataset.select(*([inp] + ([label] if label else []))).rdd
        if rdd.getNumPartitions() != n_hosts:
            rdd = rdd.repartition(n_hosts)

        from sparktorch_tpu.ft import FtPolicy, RestartPolicy

        ft_policy = (
            FtPolicy(restart=RestartPolicy(
                max_restarts=self.getOrDefault(self.ftMaxRestarts)))
            if supervise else None
        )

        # The coordinator runs HERE on the driver; barrier tasks must
        # not start their own (start_coordinator=False below). Port 0 =
        # ephemeral: two concurrent fits on one driver cannot collide;
        # the bound port travels to the tasks in the closure. Under
        # supervision the coordinator opens a rejoin grace window so a
        # restarted stage's ranks re-register on a fresh generation.
        from sparktorch_tpu.native.gang import GangCoordinator

        coord = GangCoordinator(
            world_size=n_hosts, port=0,
            rejoin_grace_ms=(int(ft_policy.rejoin_grace_s * 1000)
                             if ft_policy is not None else 0),
        )
        gang_port = coord.port

        def make_run_host(resume: bool):
            return lambda iterator: run_host(iterator, resume)

        def run_host(iterator, resume=False):
            from pyspark import BarrierTaskContext

            ctx = BarrierTaskContext.get()
            rank = ctx.partitionId()
            rows = list(iterator)
            x = _rows_to_x(rows) if rows else np.zeros((0, 1), np.float32)
            if label:
                # Empty partitions still declare the label axis so the
                # cross-host shape agreement holds (weight-0 padding
                # absorbs them — distributed.py:131-133 analog).
                y = (_labels_to_f32([r[1] for r in rows], label)
                     if rows else np.zeros((0,), np.float32))
            else:
                y = None

            from sparktorch_tpu.parallel.launch import bringup_multihost
            from sparktorch_tpu.train.sync import train_distributed_multihost

            _, worker = bringup_multihost(
                rank=rank, world_size=n_hosts, coordinator_host=gang_host,
                gang_port=gang_port, start_coordinator=False,
                ft_policy=ft_policy,
            )
            try:
                result = train_distributed_multihost(
                    torch_obj, x, local_y=y, iters=iters,
                    partition_shuffles=shuffles, verbose=verbose,
                    mini_batch=mini_batch, early_stop_patience=patience,
                    checkpoint_dir=ckpt_dir, checkpoint_every=ckpt_every,
                    resume=resume,
                )
                # The SPMD result is replicated; rank 0's copy is
                # canonical (the reference keeps collect()[0],
                # distributed.py:267-273).
                if rank == 0:
                    yield _encode_bundle(
                        result.spec, result.params, result.model_state
                    )
            finally:
                if worker is not None:
                    worker.close()  # also unregisters the liveness check

        try:
            if supervise:
                # Stage-level recovery: a dead rank fails the whole
                # barrier stage (Spark semantics); the supervisor
                # restarts the STAGE under the ft policy, resuming
                # from the latest finalized checkpoint (auto-
                # discovered), and the coordinator's rejoin grace lets
                # the new generation of ranks re-register.
                from sparktorch_tpu.ft import supervise_run

                out = supervise_run(
                    lambda attempt, resume: rdd.barrier().mapPartitions(
                        make_run_host(resume)).collect(),
                    policy=ft_policy,
                    checkpoint_dir=ckpt_dir,
                    name="spark_barrier_stage",
                )
            else:
                out = rdd.barrier().mapPartitions(
                    make_run_host(False)).collect()
        finally:
            coord.stop()
        if not out:
            raise RuntimeError("barrier training returned no model")
        return out[0]


class SparkTorchModel(Model, _SparkTorchParams, PythonStagePersistence,
                      MLReadable, MLWritable):
    """Fitted transformer. Persists inside standard Spark pipelines via
    the carrier mechanism (PythonStagePersistence — the writer hook the
    reference implements in ``pipeline_util.py:80-130``)."""

    modStr = Param(Params._dummy(), "modStr", "serialized trained model",
                   typeConverter=TypeConverters.toString)

    @keyword_only
    def __init__(self, inputCol=None, predictionCol=None, modStr=None,
                 useVectorOut=None):
        super().__init__()
        self._setDefault(predictionCol="predictions", useVectorOut=False)
        self._set(**self._input_kwargs)

    def getPytorchModel(self):
        """Decoded {spec, params, model_state} bundle
        (torch_distributed.py:92-94 parity)."""
        return _decode_bundle(self.getOrDefault(self.modStr))

    def _transform(self, dataset):
        inp = self.getOrDefault(self.inputCol)
        out_col = self.getOrDefault(self.predictionCol)
        use_vec = self.getOrDefault(self.useVectorOut)
        mod_str = self.getOrDefault(self.modStr)
        sc = dataset.sparkSession.sparkContext
        broadcast_mod = sc.broadcast(mod_str)

        # Arrow cannot serialize VectorUDT columns into a pandas_udf;
        # convert Spark ML vectors to plain arrays first.
        input_col = dataset[inp]
        try:
            from pyspark.ml.linalg import VectorUDT
            from pyspark.ml.functions import vector_to_array

            if isinstance(dataset.schema[inp].dataType, VectorUDT):
                input_col = vector_to_array(input_col)
        except ImportError:
            pass

        def make_predictor():
            from sparktorch_tpu.inference import BatchPredictor

            payload = _decode_bundle(broadcast_mod.value)
            spec = payload["spec"]
            return BatchPredictor(spec.make_module(), payload["params"],
                                  payload["model_state"])

        if use_vec:
            @pandas_udf(ArrayType(DoubleType()))
            def predict(series):
                import pandas as pd

                predictor = make_predictor()
                x = np.stack([np.asarray(v, dtype=np.float32) for v in series])
                out = predictor.predict(x)
                return pd.Series([row.astype(float).tolist() for row in out])
        else:
            @pandas_udf(DoubleType())
            def predict(series):
                import pandas as pd

                predictor = make_predictor()
                x = np.stack([np.asarray(v, dtype=np.float32) for v in series])
                out = predictor.predict(x)
                flat = out.reshape(out.shape[0], -1)
                vals = (np.argmax(flat, axis=1).astype(np.float64)
                        if flat.shape[1] > 1 else flat[:, 0].astype(np.float64))
                return pd.Series(vals)

        return dataset.withColumn(out_col, predict(input_col))
