"""Optional PySpark integration.

Everything in this subpackage requires ``pyspark`` at import time; the
core framework never imports it. The baked image for this repo does
not ship pyspark, so these modules are exercised only in environments
that provide it (the reference's deployment target).
"""
