"""PySpark deployment tier.

``torch_distributed`` / ``pipeline_util`` require a pyspark module at
import time. On a Spark cluster that is the real thing; everywhere
else :mod:`sparktorch_tpu.spark.localsession` provides a faithful
API-compatible local runtime (real multi-process executors, barrier
execution, pipeline persistence) — call ``localsession.install()``
first and the adapter code runs unmodified. The core framework
(:mod:`sparktorch_tpu.ml`) never imports any of this.
"""

__all__ = ["localsession"]

from sparktorch_tpu.spark import localsession  # noqa: E402
