"""sparktorch_tpu — a TPU-native distributed training framework.

A ground-up re-design of the capability surface of ``sparktorch``
(reference: ``/root/reference/sparktorch/__init__.py:1-4`` exports
``serialize_torch_obj``, ``serialize_torch_obj_lazy``, ``SparkTorch``,
``PysparkPipelineWrapper``, ``create_spark_torch_model``) built on
JAX/XLA/Pallas for TPU pods instead of PyTorch/gloo/Spark-JVM.

Architecture (TPU-first, not a port):

- The reference's "one gloo rank per Spark executor" data parallelism
  (``distributed.py:180-182`` per-parameter all_reduce loop) becomes a
  single jitted SPMD train step over a ``jax.sharding.Mesh``; gradient
  synchronisation is a weighted global mean that XLA lowers to ICI
  collectives — zero per-step Python on the hot path.
- The reference's Flask parameter server (``server.py``) becomes an
  HBM-resident parameter service with versioned pulls and a
  single-writer jitted apply queue (``sparktorch_tpu.serve``).
- The Spark ML ``Estimator``/``Transformer``/``Pipeline`` surface
  (``torch_distributed.py:130-349``) is provided natively (no JVM) by
  ``sparktorch_tpu.ml``, with an optional PySpark adapter.
"""

from sparktorch_tpu.utils.serde import (
    ModelSpec,
    serialize_model,
    serialize_model_lazy,
    deserialize_model,
    # Reference-compatible aliases (sparktorch/__init__.py:1-4).
    serialize_torch_obj,
    serialize_torch_obj_lazy,
)
from sparktorch_tpu.utils.data import DataBatch, handle_features
from sparktorch_tpu.utils.early_stopper import EarlyStopping
from sparktorch_tpu.parallel.mesh import MeshConfig, build_mesh
from sparktorch_tpu.ml.estimator import SparkTorch, SparkTorchModel
from sparktorch_tpu.ml.pipeline import Pipeline, PipelineModel, PysparkPipelineWrapper
from sparktorch_tpu.inference import (
    BatchPredictor,
    create_spark_torch_model,
    attach_model_to_pipeline,
    attach_pytorch_model_to_pipeline,
    convert_to_serialized,
)

__version__ = "0.1.0"

__all__ = [
    "ModelSpec",
    "serialize_model",
    "serialize_model_lazy",
    "deserialize_model",
    "serialize_torch_obj",
    "serialize_torch_obj_lazy",
    "DataBatch",
    "handle_features",
    "EarlyStopping",
    "MeshConfig",
    "build_mesh",
    "SparkTorch",
    "SparkTorchModel",
    "Pipeline",
    "PipelineModel",
    "PysparkPipelineWrapper",
    "BatchPredictor",
    "create_spark_torch_model",
    "attach_model_to_pipeline",
    "attach_pytorch_model_to_pipeline",
    "convert_to_serialized",
]
