"""The reference's ``examples/simple_dnn.py`` flow on a REAL Spark
cluster: fit through the pyspark adapter against true remote
executors (executors stream partition data to the driver; the driver
runs the compiled SPMD trainer), transform with the Arrow-batched
UDF on the executors, and round-trip the fitted pipeline through the
JVM persistence carrier.

Run inside the compose harness (deploy/docker/docker-compose.yml) or
against any standalone cluster:

    python deploy/docker/cluster_example.py --master spark://host:7077
"""

import argparse

import numpy as np


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--master", default="local[2]")
    ap.add_argument("--rows", type=int, default=2000)
    args = ap.parse_args()

    from pyspark.ml import Pipeline, PipelineModel
    from pyspark.ml.linalg import Vectors
    from pyspark.sql import SparkSession

    from sparktorch_tpu import PysparkPipelineWrapper  # noqa: F401 (API check)
    from sparktorch_tpu.models import MnistMLP
    from sparktorch_tpu.spark.pipeline_util import (
        PysparkPipelineWrapper as Wrapper,
    )
    from sparktorch_tpu.spark.torch_distributed import SparkTorch
    from sparktorch_tpu.utils.serde import serialize_model

    spark = (
        SparkSession.builder.master(args.master)
        .appName("sparktorch_tpu-cluster-example")
        .config("spark.sql.execution.arrow.pyspark.enabled", "true")
        .getOrCreate()
    )

    rng = np.random.default_rng(0)
    half = args.rows // 2
    x = np.concatenate([
        rng.normal(0.0, 1.0, (half, 10)),
        rng.normal(2.0, 1.0, (half, 10)),
    ])
    y = np.concatenate([np.zeros(half), np.ones(half)])
    perm = rng.permutation(2 * half)
    rows = [(float(y[i]), Vectors.dense(x[i].tolist())) for i in perm]
    df = spark.createDataFrame(rows, ["label", "features"]).repartition(2)

    torch_obj = serialize_model(
        MnistMLP(hidden=(32, 16), n_classes=2), "cross_entropy", "adam",
        {"lr": 1e-2}, input_shape=(10,),
    )
    est = SparkTorch(
        inputCol="features", labelCol="label", predictionCol="predictions",
        torchObj=torch_obj, iters=40, verbose=1, miniBatch=128,
    )
    model = Pipeline(stages=[est]).fit(df)
    res = model.transform(df).collect()
    preds = np.asarray([r["predictions"] for r in res])
    labels = np.asarray([r["label"] for r in res])
    acc = float(np.mean(preds == labels))
    print(f"cluster train accuracy: {acc:.4f}")
    assert acc > 0.9, f"accuracy too low: {acc}"

    path = "/tmp/sparktorch_tpu_cluster_pipe"
    model.write().overwrite().save(path)
    loaded = Wrapper.unwrap(PipelineModel.load(path))
    res2 = loaded.transform(df).collect()
    preds2 = np.asarray([r["predictions"] for r in res2])
    assert np.array_equal(preds, preds2), "persistence round trip diverged"
    print("JVM persistence round trip OK")
    spark.stop()


if __name__ == "__main__":
    main()
