// Gang coordinator: TCP rendezvous + barrier + heartbeat failure
// detection for multi-host TPU training.
//
// Role in the framework: the reference delegates gang scheduling to
// Spark's JVM barrier executor (PipelinedRDD(..., isFromBarrier=True),
// reference distributed.py:39-43) and rendezvous to gloo's TCP store on
// a hardcoded driver port (distributed.py:101-105). This library is the
// native replacement: the driver runs a coordinator; each host process
// registers (rank, address), blocks on a barrier until the world is
// complete, retrieves the peer table (whose rank-0 address seeds
// jax.distributed.initialize), and then heartbeats. A silent host is
// declared dead after a timeout and every barrier waiter is released
// with an error — failure *detection*, which the reference lacks
// entirely (SURVEY section 5: resilience is one HTTP retry).
//
// Exposed as a C API for ctypes (no pybind11 in this toolchain).
//
// Protocol (line-based over TCP):
//   REG <rank> <addr> [<gen>] [<run>]\n
//       -> OK <world_size> <gen> [<run_id>]\n | ERR <msg>\n | DEAD\n
//   BAR <epoch>\n               -> GO\n | DEAD\n
//   WLD\n                       -> <rank0 addr>,<rank1 addr>,...\n
//   HB <rank> [<gen>]\n         -> OK\n | DEAD\n
//
// The optional <run> token (run-id-tagged protocol, backward-
// compatible exactly like the generation tag below) correlates
// per-rank observability streams: a coordinator started with a
// run_id announces it in every OK reply, so each rank stamps the
// SAME gang-unique id on its spans/events/heartbeats and a fleet
// collector can join them. A client that already knows a run id
// echoes it on REG ("-" = no claim); a MISMATCHED claim is refused
// with "ERR run" — a rank from a different gang's run must not
// silently register into this one (e.g. a stale supervisor pointing
// at a recycled host:port). Old clients never send the token and old
// coordinators ignore it (sscanf stops early), so mixed-version
// gangs keep working.
//
// The optional <gen> tag (generation-tagged protocol) closes the
// rejoin-grace race: REG/HB lines carry the generation the client
// JOINED, and the coordinator refuses stale ones with DEAD. A fresh
// client tags REG with -1 ("never joined"); the OK reply carries the
// generation it joined, which the client echoes on every subsequent
// HB and reconnect-REG. During the rejoin grace window only FRESH
// registrations (gen -1, i.e. supervisor-restarted ranks — or
// untagged old-version clients) open the new generation; a survivor
// of the failed generation whose heartbeat socket broke re-REGs with
// its old tag and is told DEAD instead of silently resurrecting the
// gang under peers that still hold old-generation connections.
// Untagged lines parse exactly as before, so mixed-version gangs
// (old client/new coordinator or the reverse) keep working — an old
// coordinator simply ignores the extra token and replies "OK <ws>".

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <sys/socket.h>
#include <unistd.h>

#include <set>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

using Clock = std::chrono::steady_clock;

struct GangState {
  int world_size = 0;
  int heartbeat_timeout_ms = 0;
  // Re-registration grace window after a failure: a REG arriving
  // within this many ms of the gang being declared failed opens a NEW
  // GENERATION (failure cleared, membership reset, everyone must
  // re-register) instead of being refused with DEAD. 0 = disabled
  // (the original latch-forever behavior, still the default).
  int rejoin_grace_ms = 0;
  // Gang-unique run id announced on OK replies (empty = untagged, the
  // pre-run-id wire format). Immutable after start; safe to read
  // without the mutex.
  std::string run_id;
  std::mutex mu;
  std::condition_variable cv;
  std::map<int, std::string> members;         // rank -> addr
  std::map<int, Clock::time_point> last_beat; // rank -> last heartbeat
  std::map<long, int> barrier_count;          // epoch -> arrivals
  std::atomic<bool> failed{false};
  std::atomic<int> dead_rank{-1};
  std::atomic<long> generation{0};
  std::atomic<bool> running{true};
  Clock::time_point failed_at;  // guarded by mu
};

struct GangServer {
  int listen_fd = -1;
  int port = 0;
  GangState state;
  std::thread accept_thread;
  std::thread monitor_thread;
  std::vector<std::thread> conn_threads;
  std::set<int> conn_fds;  // live accepted sockets, for prompt shutdown
  std::mutex conn_mu;
};

bool read_line(int fd, std::string *out) {
  out->clear();
  char c;
  while (true) {
    ssize_t n = recv(fd, &c, 1, 0);
    if (n <= 0) return false;
    if (c == '\n') return true;
    out->push_back(c);
    if (out->size() > 4096) return false;
  }
}

bool write_all(int fd, const std::string &s) {
  size_t off = 0;
  while (off < s.size()) {
    ssize_t n = send(fd, s.data() + off, s.size() - off, MSG_NOSIGNAL);
    if (n <= 0) return false;
    off += static_cast<size_t>(n);
  }
  return true;
}

void handle_conn(GangServer *srv, int fd) {
  GangState &st = srv->state;
  std::string line;
  while (st.running.load() && read_line(fd, &line)) {
    if (line.rfind("REG ", 0) == 0) {
      int rank = -1;
      long gen = -1;  // -1 = fresh/untagged
      char addr[1024] = {0};
      char run[128] = {0};  // "-"/absent = no run-id claim
      int n_tok = sscanf(line.c_str(), "REG %d %1023s %ld %127s", &rank, addr,
                         &gen, run);
      if (n_tok < 2 || rank < 0 || rank >= st.world_size) {
        write_all(fd, "ERR bad rank\n");
        continue;
      }
      if (n_tok == 2) gen = -1;
      // A run-id CLAIM that contradicts this coordinator's run is a
      // rank from a different gang incarnation (stale supervisor,
      // recycled endpoint): refuse before touching membership. No
      // claim ("-"/absent) always passes — first registration happens
      // before the client can know the id.
      if (n_tok >= 4 && run[0] != '\0' && strcmp(run, "-") != 0 &&
          !st.run_id.empty() && st.run_id != run) {
        write_all(fd, "ERR run\n");
        continue;
      }
      // A failed gang stays failed — UNLESS a supervisor is restarting
      // ranks and the rejoin grace window is open: then the first
      // FRESH re-registration after the failure opens a new generation
      // (failure cleared, membership and barrier counts reset, every
      // rank must re-register), so a restarted gang can reform on the
      // same coordinator instead of being poisoned forever. Outside
      // the window (or with grace disabled) re-registration must not
      // resurrect the slot and mask the gang-wide DEAD verdict peers
      // were already told about: the dialer sees DEAD, which its
      // client treats as authoritative.
      //
      // Generation tags narrow who may (re)join:
      // - healthy gang: fresh (-1) or current-generation tags register;
      //   a STALE tag (an old-generation survivor reconnecting after
      //   a rejoin already opened a new generation) is refused DEAD.
      // - failed gang in grace: only FRESH registrations open the new
      //   generation; a tag equal to the failed generation is a
      //   surviving member of the dead gang whose socket broke — it
      //   must hear DEAD, not resurrect the gang under its peers.
      bool ok = false;
      long cur_gen = 0;
      {
        std::lock_guard<std::mutex> lock(st.mu);
        cur_gen = st.generation.load();
        if (st.failed.load()) {
          auto since = std::chrono::duration_cast<std::chrono::milliseconds>(
                           Clock::now() - st.failed_at)
                           .count();
          if (gen < 0 && st.rejoin_grace_ms > 0 &&
              since <= st.rejoin_grace_ms) {
            cur_gen = st.generation.fetch_add(1) + 1;
            st.members.clear();
            st.last_beat.clear();
            st.barrier_count.clear();
            st.failed.store(false);
            st.dead_rank.store(-1);
            ok = true;
          }
        } else if (gen < 0 || gen == cur_gen) {
          ok = true;
        }
        if (ok) {
          st.members[rank] = addr;
          st.last_beat[rank] = Clock::now();
        }
      }
      if (ok) {
        st.cv.notify_all();
        std::string reply = "OK " + std::to_string(st.world_size) + " " +
                            std::to_string(cur_gen);
        if (!st.run_id.empty()) reply += " " + st.run_id;
        write_all(fd, reply + "\n");
      } else {
        write_all(fd, "DEAD\n");
      }
    } else if (line.rfind("BAR ", 0) == 0) {
      long epoch = atol(line.c_str() + 4);
      std::unique_lock<std::mutex> lock(st.mu);
      // The generation this waiter parked under: an elastic resize
      // bumps it WITHOUT latching failure (it clears barrier_count and
      // the failure latch while waiters may still be parked), so the
      // wait must also release on a generation change — otherwise a
      // parked waiter re-evaluates (cleared count, failure unlatched)
      // to false and re-parks forever, or worse, a new generation
      // reusing this epoch number refills barrier_count[epoch] and
      // hands the stale waiter a spurious GO into a gang that no
      // longer includes it.
      long entry_gen = st.generation.load();
      st.barrier_count[epoch]++;
      st.cv.notify_all();
      st.cv.wait(lock, [&] {
        return st.barrier_count[epoch] >= st.world_size ||
               st.generation.load() != entry_gen || st.failed.load() ||
               !st.running.load();
      });
      // GO only for a genuinely complete barrier OF THIS GENERATION: a
      // waiter released by failure, resize, or coordinator shutdown
      // must see an error (it re-registers fresh), never a spurious
      // green light into a collective that will hang.
      bool complete = st.barrier_count[epoch] >= st.world_size &&
                      st.generation.load() == entry_gen;
      lock.unlock();
      write_all(fd, (complete && !st.failed.load() && st.running.load())
                        ? "GO\n"
                        : "DEAD\n");
    } else if (line.rfind("HB ", 0) == 0) {
      int rank = -1;
      long gen = -1;
      int n_tok = sscanf(line.c_str(), "HB %d %ld", &rank, &gen);
      if (n_tok < 2) gen = -1;
      // A tagged heartbeat from a PREVIOUS generation is a survivor
      // of a gang that already reformed (or failed) under it: reply
      // DEAD so it learns within one heartbeat interval, and do NOT
      // refresh the slot — its beat must not keep the reformed
      // generation's member alive. Untagged beats keep the original
      // semantics (old clients in mixed-version gangs).
      bool stale = false;
      {
        std::lock_guard<std::mutex> lock(st.mu);
        stale = gen >= 0 && gen != st.generation.load();
        if (n_tok >= 1 && !stale) st.last_beat[rank] = Clock::now();
      }
      write_all(fd, (stale || st.failed.load()) ? "DEAD\n" : "OK\n");
    } else if (line == "WLD") {
      std::string out;
      {
        std::lock_guard<std::mutex> lock(st.mu);
        for (auto &kv : st.members) {
          if (!out.empty()) out += ",";
          out += kv.second;
        }
      }
      write_all(fd, out + "\n");
    } else {
      write_all(fd, "ERR unknown\n");
    }
  }
  {
    std::lock_guard<std::mutex> lock(srv->conn_mu);
    srv->conn_fds.erase(fd);
  }
  close(fd);
}

void monitor_loop(GangServer *srv) {
  GangState &st = srv->state;
  if (st.heartbeat_timeout_ms <= 0) return;
  while (st.running.load()) {
    std::this_thread::sleep_for(
        std::chrono::milliseconds(st.heartbeat_timeout_ms / 4 + 1));
    auto now = Clock::now();
    std::lock_guard<std::mutex> lock(st.mu);
    // Only monitor once the full gang registered — a slow joiner is
    // not a failure (registration has its own timeout client-side).
    if (static_cast<int>(st.members.size()) < st.world_size) continue;
    for (auto &kv : st.last_beat) {
      auto ms = std::chrono::duration_cast<std::chrono::milliseconds>(
                    now - kv.second)
                    .count();
      if (ms > st.heartbeat_timeout_ms) {
        if (!st.failed.exchange(true)) {
          // Transition only: the grace window anchors at the FIRST
          // failure of the episode, not at every monitor sweep.
          st.failed_at = now;
        }
        st.dead_rank.store(kv.first);
        st.cv.notify_all();
      }
    }
  }
}

void accept_loop(GangServer *srv) {
  while (srv->state.running.load()) {
    int fd = accept(srv->listen_fd, nullptr, nullptr);
    if (fd < 0) {
      if (!srv->state.running.load()) break;
      continue;
    }
    std::lock_guard<std::mutex> lock(srv->conn_mu);
    srv->conn_fds.insert(fd);
    srv->conn_threads.emplace_back(handle_conn, srv, fd);
  }
}

struct GangClient {
  int fd = -1;
  int rank = -1;
  long generation = -1;  // generation joined; -1 = old/untagged server
  std::string run_id;    // announced by the OK reply; empty = untagged
};

int dial(const char *host, int port, int timeout_ms) {
  // Resolve with getaddrinfo: in real deployments the coordinator host
  // arrives as a hostname/FQDN (e.g. Spark's spark.driver.host), not an
  // IPv4 literal. getaddrinfo handles both.
  struct addrinfo hints {};
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  struct addrinfo *res = nullptr;
  std::string port_str = std::to_string(port);
  if (getaddrinfo(host, port_str.c_str(), &hints, &res) != 0 || !res)
    return -1;
  int fd = -1;
  for (struct addrinfo *ai = res; ai; ai = ai->ai_next) {
    fd = socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
    if (fd < 0) continue;
    struct timeval tv;
    tv.tv_sec = timeout_ms / 1000;
    tv.tv_usec = (timeout_ms % 1000) * 1000;
    setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
    setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
    if (connect(fd, ai->ai_addr, ai->ai_addrlen) == 0) break;
    close(fd);
    fd = -1;
  }
  freeaddrinfo(res);
  return fd;
}

}  // namespace

extern "C" {

void *gang_server_start3(int port, int world_size, int heartbeat_timeout_ms,
                         int rejoin_grace_ms, const char *run_id) {
  auto *srv = new GangServer();
  srv->state.world_size = world_size;
  srv->state.heartbeat_timeout_ms = heartbeat_timeout_ms;
  srv->state.rejoin_grace_ms = rejoin_grace_ms;
  if (run_id) srv->state.run_id = run_id;
  srv->listen_fd = socket(AF_INET, SOCK_STREAM, 0);
  if (srv->listen_fd < 0) {
    delete srv;
    return nullptr;
  }
  int one = 1;
  setsockopt(srv->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in sa{};
  sa.sin_family = AF_INET;
  sa.sin_addr.s_addr = htonl(INADDR_ANY);
  sa.sin_port = htons(static_cast<uint16_t>(port));
  if (bind(srv->listen_fd, reinterpret_cast<sockaddr *>(&sa), sizeof(sa)) != 0 ||
      listen(srv->listen_fd, 128) != 0) {
    close(srv->listen_fd);
    delete srv;
    return nullptr;
  }
  socklen_t len = sizeof(sa);
  getsockname(srv->listen_fd, reinterpret_cast<sockaddr *>(&sa), &len);
  srv->port = ntohs(sa.sin_port);
  srv->accept_thread = std::thread(accept_loop, srv);
  srv->monitor_thread = std::thread(monitor_loop, srv);
  return srv;
}

void *gang_server_start2(int port, int world_size, int heartbeat_timeout_ms,
                         int rejoin_grace_ms) {
  // Pre-run-id entry: untagged coordinator (legacy OK replies).
  return gang_server_start3(port, world_size, heartbeat_timeout_ms,
                            rejoin_grace_ms, nullptr);
}

void *gang_server_start(int port, int world_size, int heartbeat_timeout_ms) {
  // Original 3-arg entry: rejoin grace disabled (latch-forever).
  return gang_server_start2(port, world_size, heartbeat_timeout_ms, 0);
}

int gang_server_run_id(void *p, char *buf, int buflen) {
  const std::string &rid = static_cast<GangServer *>(p)->state.run_id;
  if (static_cast<int>(rid.size()) + 1 > buflen) return -1;
  memcpy(buf, rid.c_str(), rid.size() + 1);
  return static_cast<int>(rid.size());
}

int gang_server_port(void *p) { return static_cast<GangServer *>(p)->port; }

// Elastic resize: change the gang's world size LIVE. A resize is a
// membership event exactly like a rejoin-after-failure — the world the
// surviving ranks registered into no longer exists — so it reuses the
// same machinery: bump the generation, clear membership / heartbeat
// slots / barrier counts, clear the failure latch, and release every
// parked barrier waiter (they see DEAD and re-register, tagged fresh,
// into the new generation). Returns the NEW generation, or -1 on a
// bad world size. The elastic controller drives this when a rank
// exhausts its restart budget (shrink) or a new host joins (grow).
long gang_server_resize(void *p, int new_world_size) {
  if (new_world_size < 1) return -1;
  auto *srv = static_cast<GangServer *>(p);
  GangState &st = srv->state;
  long gen;
  {
    std::lock_guard<std::mutex> lock(st.mu);
    st.world_size = new_world_size;
    gen = st.generation.fetch_add(1) + 1;
    st.members.clear();
    st.last_beat.clear();
    st.barrier_count.clear();
    st.failed.store(false);
    st.dead_rank.store(-1);
    st.cv.notify_all();
  }
  return gen;
}

int gang_server_world_size(void *p) {
  auto *srv = static_cast<GangServer *>(p);
  std::lock_guard<std::mutex> lock(srv->state.mu);
  return srv->state.world_size;
}

long gang_server_generation(void *p) {
  return static_cast<GangServer *>(p)->state.generation.load();
}

int gang_server_failed(void *p) {
  return static_cast<GangServer *>(p)->state.failed.load() ? 1 : 0;
}

int gang_server_dead_rank(void *p) {
  return static_cast<GangServer *>(p)->state.dead_rank.load();
}

int gang_server_registered(void *p) {
  auto *srv = static_cast<GangServer *>(p);
  std::lock_guard<std::mutex> lock(srv->state.mu);
  return static_cast<int>(srv->state.members.size());
}

void gang_server_stop(void *p) {
  auto *srv = static_cast<GangServer *>(p);
  {
    // Store+notify under the monitor mutex: without it a BAR handler
    // can evaluate its wait predicate just before the store and then
    // block after the notify — a lost wakeup that wedges stop().
    std::lock_guard<std::mutex> lock(srv->state.mu);
    srv->state.running.store(false);
    srv->state.cv.notify_all();
  }
  shutdown(srv->listen_fd, SHUT_RDWR);
  close(srv->listen_fd);
  if (srv->accept_thread.joinable()) srv->accept_thread.join();
  if (srv->monitor_thread.joinable()) srv->monitor_thread.join();
  // Unblock handler threads parked in recv() on live client sockets —
  // a worker that died without closing its socket (the very failure the
  // coordinator detects) must not wedge stop() in join().
  {
    std::lock_guard<std::mutex> lock(srv->conn_mu);
    for (int fd : srv->conn_fds) shutdown(fd, SHUT_RDWR);
  }
  for (auto &t : srv->conn_threads)
    if (t.joinable()) t.join();
  delete srv;
}

// status (when non-null): 0 = registered, 1 = coordinator replied DEAD
// (the gang already failed — authoritative, do not retry), -1 = io/ERR.
// generation: the tag sent on the REG line (-1 = fresh, never joined;
// >=0 = rejoining member of that generation — refused once stale).
// run_id: the run claim sent on the REG line (null/empty/"-" = none);
// a mismatched claim is refused by run-id-tagged coordinators.
void *gang_client_connect4(const char *host, int port, int rank,
                           const char *addr, int timeout_ms,
                           long generation, const char *run_id,
                           int *status) {
  if (status) *status = -1;
  int fd = dial(host, port, timeout_ms);
  if (fd < 0) return nullptr;
  auto *cli = new GangClient{fd, rank};
  std::string msg = "REG " + std::to_string(rank) + " " + addr + " " +
                    std::to_string(generation);
  if (run_id && run_id[0] != '\0') msg += std::string(" ") + run_id;
  std::string resp;
  if (!write_all(fd, msg + "\n") || !read_line(fd, &resp) ||
      resp.rfind("OK", 0) != 0) {
    if (status && resp == "DEAD") *status = 1;
    close(fd);
    delete cli;
    return nullptr;
  }
  // "OK <world_size> <generation> [<run_id>]" from a tagged
  // coordinator; an old coordinator replies "OK <world_size>" and the
  // client stays untagged (generation -1 -> legacy HB lines).
  long ws = 0, gen = -1;
  char run[128] = {0};
  int n_tok = sscanf(resp.c_str(), "OK %ld %ld %127s", &ws, &gen, run);
  if (n_tok >= 2) cli->generation = gen;
  if (n_tok >= 3) cli->run_id = run;
  if (status) *status = 0;
  return cli;
}

void *gang_client_connect3(const char *host, int port, int rank,
                           const char *addr, int timeout_ms,
                           long generation, int *status) {
  return gang_client_connect4(host, port, rank, addr, timeout_ms, generation,
                              nullptr, status);
}

void *gang_client_connect2(const char *host, int port, int rank,
                           const char *addr, int timeout_ms, int *status) {
  return gang_client_connect3(host, port, rank, addr, timeout_ms, -1, status);
}

void *gang_client_connect(const char *host, int port, int rank,
                          const char *addr, int timeout_ms) {
  return gang_client_connect3(host, port, rank, addr, timeout_ms, -1, nullptr);
}

long gang_client_generation(void *p) {
  return static_cast<GangClient *>(p)->generation;
}

int gang_client_run_id(void *p, char *buf, int buflen) {
  const std::string &rid = static_cast<GangClient *>(p)->run_id;
  if (static_cast<int>(rid.size()) + 1 > buflen) return -1;
  memcpy(buf, rid.c_str(), rid.size() + 1);
  return static_cast<int>(rid.size());
}

// 0 = released, 1 = gang failure (a member died), -1 = io error.
int gang_client_barrier(void *p, long epoch) {
  auto *cli = static_cast<GangClient *>(p);
  std::string resp;
  if (!write_all(cli->fd, "BAR " + std::to_string(epoch) + "\n")) return -1;
  // Barrier waits indefinitely server-side; disable the rcv timeout
  // for this read and restore afterwards is overkill — poll lines.
  struct timeval tv {};
  tv.tv_sec = 86400;
  setsockopt(cli->fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
  if (!read_line(cli->fd, &resp)) return -1;
  return resp == "GO" ? 0 : 1;
}

int gang_client_heartbeat(void *p) {
  auto *cli = static_cast<GangClient *>(p);
  // Tagged when the coordinator speaks the tagged protocol: a beat
  // from a superseded generation then earns an authoritative DEAD.
  std::string line = "HB " + std::to_string(cli->rank);
  if (cli->generation >= 0) line += " " + std::to_string(cli->generation);
  std::string resp;
  if (!write_all(cli->fd, line + "\n")) return -1;
  if (!read_line(cli->fd, &resp)) return -1;
  return resp == "OK" ? 0 : 1;
}

int gang_client_world(void *p, char *buf, int buflen) {
  auto *cli = static_cast<GangClient *>(p);
  std::string resp;
  if (!write_all(cli->fd, "WLD\n")) return -1;
  if (!read_line(cli->fd, &resp)) return -1;
  if (static_cast<int>(resp.size()) + 1 > buflen) return -1;
  memcpy(buf, resp.c_str(), resp.size() + 1);
  return static_cast<int>(resp.size());
}

void gang_client_close(void *p) {
  auto *cli = static_cast<GangClient *>(p);
  close(cli->fd);
  delete cli;
}

}  // extern "C"
