// rowpack: multithreaded CSV -> float32 matrix parser.
//
// Role in the framework: the reference's data path goes Spark row ->
// per-row numpy conversion -> python stacking (handle_data,
// reference torch_distributed.py:43-55; handle_features util.py:57-100)
// and its examples ingest MNIST CSVs through Spark's reader. This is
// the native ingestion fast path: memory-map-free chunked reads,
// one worker thread per chunk, straight into a caller-allocated
// float32 buffer. Label column extraction is fused into the same scan.
//
// A "data row" is a line containing at least one character that is
// neither '\r' nor '\n'. Counting (scan_dims), chunk row numbering
// (rows_before) and parsing (parse_chunk) all share that definition,
// so blank lines anywhere in the file cannot skew row indices against
// the caller-allocated buffers; parse_chunk additionally bound-checks
// every row write.
//
// C API (ctypes):
//   rowpack_count(path, *rows, *cols)          -> 0 ok
//   rowpack_parse(path, out, rows, cols,
//                 label_col, labels_out, nthreads) -> rows parsed (<0 err)

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

namespace {

// Count data rows and columns of a CSV (header detected by presence
// of a non-numeric first character). Streams bytes so lines longer
// than the read buffer are still counted once.
int scan_dims(const char *path, long *rows, int *cols, long *data_start) {
  FILE *f = fopen(path, "rb");
  if (!f) return -1;
  char buf[1 << 16];
  long r = 0;
  int c = 1;
  long offset = 0;
  *data_start = 0;
  bool in_first_line = true;
  bool first_line_header = false;
  bool seen_any_char = false;
  bool line_has_data = false;
  size_t len;
  while ((len = fread(buf, 1, sizeof(buf), f)) > 0) {
    for (size_t i = 0; i < len; i++) {
      char ch = buf[i];
      if (!seen_any_char) {
        first_line_header = !(ch == '-' || ch == '+' || ch == '.' ||
                              (ch >= '0' && ch <= '9'));
        seen_any_char = true;
      }
      if (ch == '\n') {
        if (in_first_line) {
          if (first_line_header)
            *data_start = offset + static_cast<long>(i) + 1;
          else if (line_has_data)
            r++;
          in_first_line = false;
        } else if (line_has_data) {
          r++;
        }
        line_has_data = false;
      } else {
        if (in_first_line && ch == ',') c++;
        if (ch != '\r') line_has_data = true;
      }
    }
    offset += static_cast<long>(len);
  }
  fclose(f);
  // Final line without a trailing newline.
  if (line_has_data && !(in_first_line && first_line_header)) r++;
  *rows = r;
  *cols = c;
  return 0;
}

void parse_chunk(const char *data, size_t begin, size_t end, size_t total,
                 long row_begin, long rows, int cols, int label_col,
                 float *out, float *labels) {
  // Advance to the start of the next full line unless at a boundary.
  size_t pos = begin;
  if (pos != 0) {
    while (pos < end && data[pos - 1] != '\n') pos++;
  }
  long row = row_begin;
  int out_cols = (label_col >= 0 ? cols - 1 : cols);
  while (pos < total && pos < end) {
    // Find this line's extent and whether it holds any data; blank
    // lines are not rows (matching scan_dims/rows_before).
    size_t eol = pos;
    bool has_data = false;
    while (eol < total && data[eol] != '\n') {
      if (data[eol] != '\r') has_data = true;
      eol++;
    }
    if (has_data) {
      if (row >= rows) break;  // never write past the caller's buffers
      int col = 0, out_col = 0;
      bool label_set = false;
      const char *p = data + pos;
      const char *line_end = data + eol;
      char *next = nullptr;
      while (col < cols) {
        float v = strtof(p, &next);
        // strtof skips whitespace including newlines: reject a parse
        // that escaped this line (short/malformed row).
        if (next == p || next > line_end) break;
        if (col == label_col && labels) {
          labels[row] = v;
          label_set = true;
        } else if (out_col < out_cols) {
          out[row * out_cols + out_col] = v;
          out_col++;
        }
        p = next;
        if (p < line_end && *p == ',') p++;
        col++;
      }
      // Short/malformed rows: zero-fill the remainder so callers
      // (who pass uninitialized buffers) see deterministic values.
      for (; out_col < out_cols; out_col++) out[row * out_cols + out_col] = 0.0f;
      if (labels && label_col >= 0 && !label_set) labels[row] = 0.0f;
      row++;
    }
    pos = eol + 1;  // past newline (or to total at EOF)
  }
}

// Data-row index at a byte offset: lines with content before it.
long rows_before(const char *data, size_t upto) {
  long n = 0;
  bool line_has_data = false;
  for (size_t i = 0; i < upto; i++) {
    if (data[i] == '\n') {
      if (line_has_data) n++;
      line_has_data = false;
    } else if (data[i] != '\r') {
      line_has_data = true;
    }
  }
  return n;
}

}  // namespace

extern "C" {

int rowpack_count(const char *path, long *rows, int *cols) {
  long ds;
  return scan_dims(path, rows, cols, &ds);
}

long rowpack_parse(const char *path, float *out, long rows, int cols,
                   int label_col, float *labels, int nthreads) {
  FILE *f = fopen(path, "rb");
  if (!f) return -1;
  fseek(f, 0, SEEK_END);
  long size = ftell(f);
  fseek(f, 0, SEEK_SET);
  std::vector<char> data(static_cast<size_t>(size) + 1);
  if (fread(data.data(), 1, static_cast<size_t>(size), f) !=
      static_cast<size_t>(size)) {
    fclose(f);
    return -1;
  }
  fclose(f);
  data[static_cast<size_t>(size)] = '\0';

  // Skip a header line if present.
  size_t start = 0;
  char ch = data[0];
  if (size > 0 &&
      !(ch == '-' || ch == '+' || ch == '.' || (ch >= '0' && ch <= '9'))) {
    while (start < static_cast<size_t>(size) && data[start] != '\n') start++;
    start++;
  }

  if (nthreads <= 0) nthreads = static_cast<int>(
      std::max(1u, std::thread::hardware_concurrency()));
  size_t span = (static_cast<size_t>(size) - start) /
                    static_cast<size_t>(nthreads) + 1;

  // Newline-aligned chunk bounds: every line belongs to exactly one
  // chunk, so per-chunk row counts can run in parallel and a prefix
  // sum yields each chunk's starting row — one parallel pass instead
  // of an O(nthreads * file) serial rescan per chunk.
  std::vector<size_t> bounds{start};
  for (int t = 1; t < nthreads; t++) {
    size_t b = start + static_cast<size_t>(t) * span;
    if (b >= static_cast<size_t>(size)) break;
    while (b < static_cast<size_t>(size) && data[b - 1] != '\n') b++;
    if (b > bounds.back() && b < static_cast<size_t>(size)) bounds.push_back(b);
  }
  bounds.push_back(static_cast<size_t>(size));
  int nchunks = static_cast<int>(bounds.size()) - 1;

  std::vector<long> counts(static_cast<size_t>(nchunks), 0);
  {
    std::vector<std::thread> counters;
    for (int i = 0; i + 1 < nchunks; i++) {  // last chunk's count unused
      counters.emplace_back([&, i] {
        counts[static_cast<size_t>(i)] =
            rows_before(data.data() + bounds[static_cast<size_t>(i)],
                        bounds[static_cast<size_t>(i) + 1] -
                            bounds[static_cast<size_t>(i)]);
      });
    }
    for (auto &w : counters) w.join();
  }

  std::vector<std::thread> workers;
  long row_begin = 0;
  for (int i = 0; i < nchunks; i++) {
    workers.emplace_back(parse_chunk, data.data(), bounds[static_cast<size_t>(i)],
                         bounds[static_cast<size_t>(i) + 1],
                         static_cast<size_t>(size), row_begin, rows, cols,
                         label_col, out, labels);
    row_begin += counts[static_cast<size_t>(i)];
  }
  for (auto &w : workers) w.join();
  return rows;
}

}  // extern "C"
