// rowpack: multithreaded CSV -> float32 matrix parser.
//
// Role in the framework: the reference's data path goes Spark row ->
// per-row numpy conversion -> python stacking (handle_data,
// reference torch_distributed.py:43-55; handle_features util.py:57-100)
// and its examples ingest MNIST CSVs through Spark's reader. This is
// the native ingestion fast path: memory-map-free chunked reads,
// one worker thread per chunk, straight into a caller-allocated
// float32 buffer. Label column extraction is fused into the same scan.
//
// C API (ctypes):
//   rowpack_count(path, *rows, *cols)          -> 0 ok
//   rowpack_parse(path, out, rows, cols,
//                 label_col, labels_out, nthreads) -> rows parsed (<0 err)

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

namespace {

// Count data rows and columns of a CSV (header detected by presence
// of a non-numeric first field).
int scan_dims(const char *path, long *rows, int *cols, long *data_start) {
  FILE *f = fopen(path, "rb");
  if (!f) return -1;
  std::string line;
  char buf[1 << 16];
  long r = 0;
  int c = 0;
  long offset = 0;
  *data_start = 0;
  bool first = true;
  while (fgets(buf, sizeof(buf), f)) {
    size_t len = strlen(buf);
    if (first) {
      // Column count from the first line.
      c = 1;
      for (size_t i = 0; i < len; i++)
        if (buf[i] == ',') c++;
      // Header? first char not numeric/[-+.].
      char ch = buf[0];
      bool header = !(ch == '-' || ch == '+' || ch == '.' ||
                      (ch >= '0' && ch <= '9'));
      if (header) *data_start = static_cast<long>(len);
      else r++;
      first = false;
    } else if (len > 1) {
      r++;
    }
    offset += static_cast<long>(len);
  }
  fclose(f);
  *rows = r;
  *cols = c;
  return 0;
}

void parse_chunk(const char *data, size_t begin, size_t end, size_t total,
                 long row_begin, int cols, int label_col, float *out,
                 float *labels) {
  // Advance to the start of the next full line unless at a boundary.
  size_t pos = begin;
  if (pos != 0) {
    while (pos < end && data[pos - 1] != '\n') pos++;
  }
  long row = row_begin;
  while (pos < total && pos < end) {
    // Parse one line.
    int col = 0, out_col = 0;
    const char *p = data + pos;
    char *next = nullptr;
    while (col < cols) {
      float v = strtof(p, &next);
      if (next == p) break;
      if (col == label_col && labels) {
        labels[row] = v;
      } else {
        out[row * (label_col >= 0 ? cols - 1 : cols) + out_col] = v;
        out_col++;
      }
      p = next;
      if (*p == ',') p++;
      col++;
    }
    while (pos < total && data[pos] != '\n') pos++;
    pos++;  // past newline
    row++;
  }
}

// Row index at a byte offset: count newlines before it.
long rows_before(const char *data, size_t upto) {
  long n = 0;
  for (size_t i = 0; i < upto; i++)
    if (data[i] == '\n') n++;
  return n;
}

}  // namespace

extern "C" {

int rowpack_count(const char *path, long *rows, int *cols) {
  long ds;
  return scan_dims(path, rows, cols, &ds);
}

long rowpack_parse(const char *path, float *out, long rows, int cols,
                   int label_col, float *labels, int nthreads) {
  FILE *f = fopen(path, "rb");
  if (!f) return -1;
  fseek(f, 0, SEEK_END);
  long size = ftell(f);
  fseek(f, 0, SEEK_SET);
  std::vector<char> data(static_cast<size_t>(size) + 1);
  if (fread(data.data(), 1, static_cast<size_t>(size), f) !=
      static_cast<size_t>(size)) {
    fclose(f);
    return -1;
  }
  fclose(f);
  data[static_cast<size_t>(size)] = '\0';

  // Skip a header line if present.
  size_t start = 0;
  char ch = data[0];
  if (!(ch == '-' || ch == '+' || ch == '.' || (ch >= '0' && ch <= '9'))) {
    while (start < static_cast<size_t>(size) && data[start] != '\n') start++;
    start++;
  }

  if (nthreads <= 0) nthreads = static_cast<int>(
      std::max(1u, std::thread::hardware_concurrency()));
  size_t span = (static_cast<size_t>(size) - start) /
                    static_cast<size_t>(nthreads) + 1;
  std::vector<std::thread> workers;
  for (int t = 0; t < nthreads; t++) {
    size_t begin = start + static_cast<size_t>(t) * span;
    size_t end = std::min(static_cast<size_t>(size), begin + span);
    if (begin >= static_cast<size_t>(size)) break;
    // Row index where this chunk's first full line starts.
    size_t aligned = begin;
    if (aligned != start) {
      while (aligned < end && data[aligned - 1] != '\n') aligned++;
    }
    long row_begin = rows_before(data.data() + start, aligned - start);
    workers.emplace_back(parse_chunk, data.data(), begin, end,
                         static_cast<size_t>(size), row_begin, cols,
                         label_col, out, labels);
  }
  for (auto &w : workers) w.join();
  return rows;
}

}  // extern "C"
