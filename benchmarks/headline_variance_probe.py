"""Variance probe for the headline MNIST-CNN bench.

Round-3 problem: the driver-captured headline spanned 289k-375k
examples/sec/chip across same-day runs (+-13%) despite a min-of-8-
chunks estimator, so a real regression is indistinguishable from
noise. This probe gathers the data to find the variance source:

- per-chunk times WITH a blocking materialize per chunk (the r03
  estimator) vs ONE materialize at the end of a long dispatch span
  (amortizes the tunnel round-trip out of the estimate);
- several steps_per_call settings (dispatch-RTT amortization);
- everything timestamped and repeated over minutes, so bursty tunnel
  congestion shows up as time-correlated slow chunks.

Writes raw records to benchmarks/headline_probe.jsonl.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np


def main() -> None:
    import jax

    jax.config.update("jax_compilation_cache_dir",
                      "/tmp/sparktorch_tpu_jit_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    from sparktorch_tpu.models import MnistCNN
    from sparktorch_tpu.parallel.mesh import MeshConfig, build_mesh, replicated
    from sparktorch_tpu.train.step import create_train_state, make_train_epoch
    from sparktorch_tpu.train.sync import prepare_sharded_batch
    from sparktorch_tpu.utils.data import handle_features
    from sparktorch_tpu.utils.serde import ModelSpec

    out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "headline_probe.jsonl")
    rng = np.random.default_rng(0)
    batch = 1024
    x = rng.normal(0, 1, (batch, 784)).astype(np.float32)
    y = rng.integers(0, 10, (batch,)).astype(np.int32)
    spec = ModelSpec(module=MnistCNN(), loss="cross_entropy",
                     optimizer="adam", optimizer_params={"lr": 1e-3},
                     input_shape=(784,))

    devices = jax.devices()
    mesh = build_mesh(MeshConfig(), devices)
    b, _ = handle_features(x, y)
    b = prepare_sharded_batch(b, mesh)
    tx = spec.make_optimizer()
    with mesh:
        state = jax.jit(
            lambda: create_train_state(spec, jax.random.key(0),
                                       sample_x=b.x[:1], tx=tx),
            out_shardings=replicated(mesh),
        )()

    apply_fn = spec.make_module().apply
    loss_fn = spec.loss_fn()

    def mat(m):
        float(np.asarray(jax.device_get(m.loss))[-1])

    records = []

    def emit(rec):
        rec["ts"] = round(time.time(), 3)
        records.append(rec)
        with open(out_path, "a") as f:
            f.write(json.dumps(rec) + "\n")
        print(json.dumps(rec), flush=True)

    epochs = {}
    # The epoch donates its input state: thread ONE state through every
    # call (rates don't depend on param values).
    for spc in (30, 120):
        epochs[spc] = make_train_epoch(apply_fn, loss_fn, tx, mesh,
                                       steps_per_call=spc)
        for _ in range(3):
            state, m = epochs[spc](state, b)
        mat(m)

    # ~4 minutes of alternating trials.
    for trial in range(8):
        # A: r03 estimator — 8 chunks of 30, materialize per chunk.
        ep = epochs[30]
        chunk_times = []
        for _ in range(8):
            t0 = time.perf_counter()
            state, m = ep(state, b)
            mat(m)
            chunk_times.append(time.perf_counter() - t0)
        per_step = [t / 30 for t in chunk_times]
        emit({"mode": "per_chunk_mat", "spc": 30, "trial": trial,
              "chunk_ms": [round(t * 1e3, 2) for t in chunk_times],
              "rate_min": round(batch / min(per_step), 0),
              "rate_med": round(batch / float(np.median(per_step)), 0)})

        # B: one long span — 8 calls of 30 dispatched back-to-back,
        # single materialize at the end.
        t0 = time.perf_counter()
        for _ in range(8):
            state, m = ep(state, b)
        mat(m)
        dt = time.perf_counter() - t0
        emit({"mode": "span_mat", "spc": 30, "trial": trial,
              "span_ms": round(dt * 1e3, 2),
              "rate": round(batch / (dt / 240), 0)})

        # C: bigger fused call — 2 calls of 120, one materialize.
        ep2 = epochs[120]
        t0 = time.perf_counter()
        for _ in range(2):
            state, m = ep2(state, b)
        mat(m)
        dt = time.perf_counter() - t0
        emit({"mode": "span_mat", "spc": 120, "trial": trial,
              "span_ms": round(dt * 1e3, 2),
              "rate": round(batch / (dt / 240), 0)})

    # Summary over trials.
    for key in [("per_chunk_mat", 30), ("span_mat", 30), ("span_mat", 120)]:
        sel = [r for r in records
               if (r["mode"], r["spc"]) == key]
        rates = [r.get("rate", r.get("rate_min")) for r in sel]
        print(f"summary mode={key[0]} spc={key[1]} "
              f"min={min(rates):.0f} med={np.median(rates):.0f} "
              f"max={max(rates):.0f} "
              f"spread={(max(rates) - min(rates)) / np.median(rates) * 100:.1f}%",
              flush=True)


if __name__ == "__main__":
    main()
