"""BASELINE config 5 at the titular scale: 1,000,000 rows of
ResNet-50 inference through the columnar streaming path, measured end
to end on the real chip — no ``projected_`` anything.

Disk reality: 1M rows of 224x224x3 uint8 = 150.5 GB, which does not
fit this rig's free disk (~79 GB). The dataset is therefore a
``--dataset-rows`` Parquet file (default 400k rows = 60 GB, the
largest that fits with headroom) streamed in consecutive passes until
1M rows have gone disk -> decode -> host->device wire -> compiled
forward -> argmax readback. Every row of every pass does the full
traversal; per-pass rates are reported separately so any page-cache
effect on later passes is visible rather than hidden (the measured
bottleneck is the host->device wire, not disk — see the saturation
analysis in the output row).

Resumable: progress (total rows done) is checkpointed to a state file
after every drained batch; rerunning with the same --state resumes
mid-pass by skipping already-processed rows of the current pass.

Usage: python benchmarks/stream_inference_1m.py [--rows 1000000]
       [--dataset-rows 400000] [--data /path.parquet]
       [--state /path.json] [--out benchmarks/bench_r04_tpu.jsonl]
"""

from __future__ import annotations

import argparse
import json
import os
import time

import numpy as np

ROW_SHAPE = (224, 224, 3)
ROW_BYTES = int(np.prod(ROW_SHAPE))


def ensure_dataset(path: str, rows: int) -> int:
    from sparktorch_tpu.inference import write_rows_parquet

    if os.path.exists(path):
        import pyarrow.parquet as pq

        have = pq.ParquetFile(path).metadata.num_rows
        if have >= rows:
            print(f"dataset: {path} already has {have} rows", flush=True)
            return have
        os.remove(path)
    print(f"dataset: generating {rows} uint8 rows {ROW_SHAPE} -> {path}",
          flush=True)
    rng = np.random.default_rng(0)
    gen_chunk = 512

    def gen():
        done = 0
        while done < rows:
            n = min(gen_chunk, rows - done)
            yield rng.integers(0, 256, (n, *ROW_SHAPE), dtype=np.uint8)
            done += n

    t0 = time.perf_counter()
    total = write_rows_parquet(path, gen(), rows_per_group=gen_chunk)
    print(f"dataset: wrote {total} rows in {time.perf_counter() - t0:.1f}s",
          flush=True)
    return total


def load_state(path: str) -> dict:
    if path and os.path.exists(path):
        with open(path) as f:
            return json.load(f)
    return {"rows_done": 0, "elapsed_s": 0.0, "pass_rows": [], "pass_s": []}


def save_state(path: str, st: dict) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(st, f)
    os.replace(tmp, path)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=1_000_000)
    ap.add_argument("--dataset-rows", type=int, default=400_000)
    ap.add_argument("--data", default="/root/stream_bench_1m_src.parquet")
    ap.add_argument("--state", default="/root/stream_1m_state.json")
    ap.add_argument(
        "--out",
        default=os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "bench_r04_tpu.jsonl"),
    )
    ap.add_argument("--chunk", type=int, default=256)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    jax.config.update("jax_compilation_cache_dir",
                      "/tmp/sparktorch_tpu_jit_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    from sparktorch_tpu.inference import BatchPredictor, stream_parquet_predict
    from sparktorch_tpu.models.resnet import resnet50

    backend = jax.default_backend()
    n_chips = len(jax.devices())
    print(f"backend={backend} devices={n_chips}", flush=True)

    have = ensure_dataset(args.data, args.dataset_rows)
    dataset_rows = min(have, args.dataset_rows)

    module = resnet50()
    variables = module.init(
        jax.random.key(0), np.zeros((1, *ROW_SHAPE), np.float32)
    )
    predictor = BatchPredictor(
        module, variables["params"],
        {k: v for k, v in variables.items() if k != "params"},
        chunk=args.chunk,
        preprocess=lambda x: x.astype(jnp.float32) / 255.0,
        # Device-side argmax (the reference's predict_float semantics,
        # torch_distributed.py:112-120): one class id per row on the
        # readback wire, not 1000 logits.
        postprocess=lambda y: jnp.argmax(y, axis=-1).astype(jnp.int32),
    )
    # ZERO device->host readbacks until the very end: on this rig the
    # tunnel's upload fast-path degrades ~50x after the FIRST readback
    # of any size (see BatchPredictor.predict_device), so warmup and
    # the chip-rate probe use the device-output path + block_until_
    # ready (a sync, not a transfer).
    out = predictor.predict_device(
        np.zeros((args.chunk, *ROW_SHAPE), np.uint8)
    )
    out.block_until_ready()  # compile fence

    # Device-resident chip rate (per-chip ceiling with colocated data).
    warm = np.random.default_rng(1).integers(
        0, 256, (4 * args.chunk, *ROW_SHAPE), dtype=np.uint8
    )
    xd = jax.device_put(warm)
    xd.block_until_ready()
    chip_rates = []
    for _ in range(3):
        t0 = time.perf_counter()
        predictor.predict_device(xd).block_until_ready()
        chip_rates.append(warm.shape[0] / (time.perf_counter() - t0))
    chip_rate = max(chip_rates) / n_chips
    print(f"chip rate (device-resident): {chip_rate:.1f} rows/s/chip",
          flush=True)

    # Predictions accumulate into ONE device buffer (int32 per row =
    # 4 MB at 1M rows) via a donated dynamic_update_slice; the single
    # download happens after the stream, when upload speed no longer
    # matters.
    result_buf = jnp.zeros((args.rows,), jnp.int32)

    _acc = jax.jit(
        lambda buf, vals, off: jax.lax.dynamic_update_slice(
            buf, vals, (off,)
        ),
        donate_argnums=(0,),
    )

    st = load_state(args.state)
    print(f"resume state: {st['rows_done']} rows already done", flush=True)

    base_elapsed = float(st.get("elapsed_s", 0.0))
    t_run0 = time.perf_counter()
    last_save = [t_run0]
    nonlocal_buf = [result_buf]

    def snapshot():
        st["elapsed_s"] = base_elapsed + (time.perf_counter() - t_run0)
        save_state(args.state, st)

    while st["rows_done"] < args.rows:
        pass_start_rows = st["rows_done"]
        offset_in_pass = st["rows_done"] % dataset_rows
        want = min(dataset_rows - offset_in_pass,
                   args.rows - st["rows_done"])

        def drain(out):
            # `out` is a DEVICE array (no readback here — see above);
            # park it in the big on-device result buffer.
            nonlocal_buf[0] = _acc(nonlocal_buf[0], out,
                                   st["rows_done"] % args.rows)
            st["rows_done"] += out.shape[0]
            now = time.perf_counter()
            if now - last_save[0] >= 30.0:
                last_save[0] = now
                snapshot()
                rate = st["rows_done"] / max(1e-9, st["elapsed_s"])
                print(f"progress: {st['rows_done']}/{args.rows} rows "
                      f"(cum {rate:.1f} rows/s)", flush=True)

        t_pass0 = time.perf_counter()
        stats = stream_parquet_predict(
            predictor, args.data, row_shape=ROW_SHAPE, dtype=np.uint8,
            batch_rows=4 * args.chunk, drain=drain,
            skip_rows=offset_in_pass, max_rows=want,
            device_outputs=True,
        )
        dt_pass = time.perf_counter() - t_pass0
        st["pass_rows"].append(st["rows_done"] - pass_start_rows)
        st["pass_s"].append(round(dt_pass, 2))
        snapshot()
        print(f"pass segment: {stats['n_rows']} rows in {dt_pass:.1f}s "
              f"({stats['n_rows']/max(dt_pass,1e-9):.1f} rows/s) "
              f"read_busy={stats['read_busy_s']}s "
              f"predict_busy={stats['predict_busy_s']}s", flush=True)

    # The ONE download: every prediction, after the stream. Included
    # in the wall via the state's elapsed accounting below.
    t_dl = time.perf_counter()
    preds = np.asarray(nonlocal_buf[0])
    dl_s = time.perf_counter() - t_dl
    st["elapsed_s"] = base_elapsed + (time.perf_counter() - t_run0)
    save_state(args.state, st)
    print(f"final download: {preds.nbytes/1e6:.1f} MB of predictions "
          f"in {dl_s:.2f}s (class histogram head: "
          f"{np.bincount(preds[:10000] % 10)[:5].tolist()})", flush=True)

    wall = st["elapsed_s"]
    rate = st["rows_done"] / max(wall, 1e-9)
    wire_mb_s = rate * ROW_BYTES / 1e6
    row = {
        "config": "resnet50_inference_stream",
        "unit": "rows/sec end-to-end",
        "backend": backend,
        "n_chips": n_chips,
        "n_rows": st["rows_done"],
        "dataset_rows": dataset_rows,
        "passes": [int(r) for r in st["pass_rows"]],
        "pass_seconds": st["pass_s"],
        "pass_rates": [
            round(r / max(s, 1e-9), 1)
            for r, s in zip(st["pass_rows"], st["pass_s"])
        ],
        "wall_s": round(wall, 1),
        "rows_per_sec": round(rate, 2),
        "steady_rows_per_sec": round(rate, 2),
        "wire_MB_per_sec": round(wire_mb_s, 1),
        "chip_rate_rows_per_sec_per_chip": round(chip_rate, 1),
        "chip_busy_fraction": round(rate / (chip_rate * n_chips), 3),
        "wire_dtype": "uint8 (normalize + argmax fused on device)",
        "ts": time.strftime("%Y-%m-%dT%H:%M:%S"),
    }
    print(json.dumps(row), flush=True)
    with open(args.out, "a") as f:
        f.write(json.dumps(row) + "\n")


if __name__ == "__main__":
    main()
