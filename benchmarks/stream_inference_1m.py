"""BASELINE config 5 at the titular scale: 1,000,000 rows of
ResNet-50 inference through the columnar streaming path, measured end
to end on the real chip — no ``projected_`` anything.

Disk reality: 1M rows of 224x224x3 uint8 = 150.5 GB, which does not
fit this rig's free disk (~79 GB). The dataset is therefore a
``--dataset-rows`` Parquet file (default 400k rows = 60 GB, the
largest that fits with headroom) streamed in consecutive passes until
1M rows have gone disk -> decode -> host->device wire -> compiled
forward -> argmax readback. Every row of every pass does the full
traversal; per-pass rates are reported separately so any page-cache
effect on later passes is visible rather than hidden (the measured
bottleneck is the host->device wire, not disk — see the saturation
analysis in the output row).

Resumable: progress (total rows done) is checkpointed to a state file
after every drained batch; rerunning with the same --state resumes
mid-pass by skipping already-processed rows of the current pass.

Usage: python benchmarks/stream_inference_1m.py [--rows 1000000]
       [--dataset-rows 400000] [--data /path.parquet]
       [--state /path.json] [--out benchmarks/bench_r04_tpu.jsonl]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

ROW_SHAPE = (224, 224, 3)
ROW_BYTES = int(np.prod(ROW_SHAPE))


def rss_gb() -> float:
    """Current process anon RSS in GB (0.0 when /proc is unreadable)."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) / 1e6  # kB -> GB
    except (OSError, ValueError, IndexError):
        pass
    return 0.0


def stall_watchdog_loop(get_fenced, is_streaming, timeout_s: float,
                        on_stall, sleep_s: float = 10.0,
                        clock=time.monotonic, sleep=time.sleep) -> None:
    """Fire ``on_stall()`` when fenced progress freezes for
    ``timeout_s`` while streaming is active. The round-5 wire stall:
    a fence readback simply never returned (12+ minutes, process
    alive, zero progress) and needed an operator kill — this loop is
    that operator. The timer resets on ANY fenced progress and while
    streaming is inactive — and "streaming" arms only at the FIRST
    drained batch of a pass, so dataset gen, compile, the final
    download AND the resume skip-scan (minutes of reader decode at
    large offsets, zero fenced progress by design) can't
    false-positive. Runs on a daemon
    thread; during a real stall the main thread is BLOCKED inside the
    dead fence, so the state it snapshots is quiescent. Injectable
    clock/sleep for tests; returns when on_stall() returns (the real
    on_stall execv's and never does)."""
    last_rows, last_t = get_fenced(), clock()
    while True:
        sleep(sleep_s)
        if not is_streaming():
            last_rows, last_t = get_fenced(), clock()
            continue
        now_rows = get_fenced()
        if now_rows != last_rows:
            last_rows, last_t = now_rows, clock()
        elif clock() - last_t > timeout_s:
            on_stall()
            return


def ensure_dataset(path: str, rows: int) -> int:
    from sparktorch_tpu.inference import write_rows_parquet

    if os.path.exists(path):
        import pyarrow.parquet as pq

        have = pq.ParquetFile(path).metadata.num_rows
        if have >= rows:
            print(f"dataset: {path} already has {have} rows", flush=True)
            return have
        os.remove(path)
    print(f"dataset: generating {rows} uint8 rows {ROW_SHAPE} -> {path}",
          flush=True)
    rng = np.random.default_rng(0)
    gen_chunk = 512

    def gen():
        done = 0
        while done < rows:
            n = min(gen_chunk, rows - done)
            yield rng.integers(0, 256, (n, *ROW_SHAPE), dtype=np.uint8)
            done += n

    t0 = time.perf_counter()
    total = write_rows_parquet(path, gen(), rows_per_group=gen_chunk)
    print(f"dataset: wrote {total} rows in {time.perf_counter() - t0:.1f}s",
          flush=True)
    return total


def load_state(path: str) -> dict:
    if path and os.path.exists(path):
        with open(path) as f:
            return json.load(f)
    return {"rows_done": 0, "elapsed_s": 0.0, "pass_rows": [],
            "pass_s": [], "restarts": 0}


def save_state(path: str, st: dict) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(st, f)
    os.replace(tmp, path)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=1_000_000)
    ap.add_argument("--dataset-rows", type=int, default=400_000)
    ap.add_argument("--data", default="/root/stream_bench_1m_src.parquet")
    ap.add_argument("--state", default="/root/stream_1m_state.json")
    ap.add_argument(
        "--out",
        default=os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "bench_r05_tpu.jsonl"),
    )
    ap.add_argument("--chunk", type=int, default=256)
    ap.add_argument(
        "--rss-limit-gb", type=float, default=48.0,
        help="exec-restart (resuming from the fenced state) when host "
        "RSS exceeds this — automates the mitigation for the tunnel "
        "client's upload-staging leak (~150 KB retained per uploaded "
        "row; 0 disables)",
    )
    ap.add_argument(
        "--stall-timeout-s", type=float, default=600.0,
        help="exec-restart when FENCED progress freezes this long mid-"
        "stream (the tunnel wire can stall outright, leaving a fence "
        "readback that never returns; 0 disables)",
    )
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    jax.config.update("jax_compilation_cache_dir",
                      "/tmp/sparktorch_tpu_jit_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    from sparktorch_tpu.inference import BatchPredictor, stream_parquet_predict
    from sparktorch_tpu.models.resnet import resnet50

    # A self-restart hands the chip grant back via process teardown;
    # the fresh image can race the release for a few seconds.
    for attempt in range(10):
        try:
            backend = jax.default_backend()
            n_chips = len(jax.devices())
            break
        except RuntimeError as e:
            print(f"backend init retry {attempt + 1}/10: {e}", flush=True)
            time.sleep(3)
    else:
        raise RuntimeError("could not initialize the TPU backend")
    print(f"backend={backend} devices={n_chips} rss={rss_gb():.1f}GB",
          flush=True)

    have = ensure_dataset(args.data, args.dataset_rows)
    dataset_rows = min(have, args.dataset_rows)

    module = resnet50()
    variables = module.init(
        jax.random.key(0), np.zeros((1, *ROW_SHAPE), np.float32)
    )
    predictor = BatchPredictor(
        module, variables["params"],
        {k: v for k, v in variables.items() if k != "params"},
        chunk=args.chunk,
        preprocess=lambda x: x.astype(jnp.float32) / 255.0,
        # Device-side argmax (the reference's predict_float semantics,
        # torch_distributed.py:112-120): one class id per row on the
        # readback wire, not 1000 logits.
        postprocess=lambda y: jnp.argmax(y, axis=-1).astype(jnp.int32),
    )
    # Honest timing discipline (see ROUND4_NOTES): on this rig's
    # tunnel, dispatch and block_until_ready both under-report — only
    # a data-dependent scalar readback truly fences. Everything below
    # that claims a rate ends in a float(jnp.sum(...)) fence.
    out = predictor.predict_device(
        np.zeros((args.chunk, *ROW_SHAPE), np.uint8)
    )
    float(jnp.sum(out))  # compile + honest fence

    # Device-resident chip rate via a PAIRED-SIZE slope (the fence
    # round-trip cancels): T(16 chunks) - T(4 chunks) over the extra
    # 12 chunks of pure compute.
    warm = np.random.default_rng(1).integers(
        0, 256, (16 * args.chunk, *ROW_SHAPE), dtype=np.uint8
    )
    xd = jax.device_put(warm)
    float(jnp.sum(predictor.predict_device(xd[: 4 * args.chunk])))  # warm
    t0 = time.perf_counter()
    float(jnp.sum(predictor.predict_device(xd[: 4 * args.chunk])))
    t_small = time.perf_counter() - t0
    t0 = time.perf_counter()
    float(jnp.sum(predictor.predict_device(xd)))
    t_big = time.perf_counter() - t0
    chip_rate = 12 * args.chunk / max(t_big - t_small, 1e-9) / n_chips
    del xd, warm
    print(f"chip rate (device-resident, paired-size slope): "
          f"{chip_rate:.1f} rows/s/chip", flush=True)

    # Predictions accumulate into ONE device buffer (int32 per row =
    # 4 MB at 1M rows) via a donated dynamic_update_slice; the single
    # download happens after the stream, when upload speed no longer
    # matters.
    result_buf = jnp.zeros((args.rows,), jnp.int32)

    _acc = jax.jit(
        lambda buf, vals, off: jax.lax.dynamic_update_slice(
            buf, vals, (off,)
        ),
        donate_argnums=(0,),
    )

    st = load_state(args.state)
    resume_start = int(st["rows_done"])
    print(f"resume state: {resume_start} rows already done", flush=True)
    if resume_start >= args.rows:
        # Re-invoked after completion: nothing to run, and appending a
        # no-work row (with an all-zeros histogram from the fresh
        # buffer) would corrupt the log.
        print(f"already complete ({resume_start} >= {args.rows}); "
              f"nothing to do — see {args.out}", flush=True)
        return
    if resume_start:
        print("note: predictions for pre-resume rows are not retained "
              "across processes (rate metrics are; the final histogram "
              "covers only this process's rows)", flush=True)

    base_elapsed = float(st.get("elapsed_s", 0.0))
    if "exec_ts" in st:
        # A self-restart persisted its wall clock just before execv:
        # everything since — backend re-init retries, model init,
        # compile, the chip-rate probe — is end-to-end wall and must
        # not vanish from elapsed (the 'measured end to end' contract).
        base_elapsed += max(0.0, time.time() - float(st.pop("exec_ts")))
        save_state(args.state, st)
    t_run0 = time.perf_counter()
    last_save = [t_run0]
    nonlocal_buf = [result_buf]
    pending_fence = [None]

    # Two counters: rows_done advances at DISPATCH (it drives the
    # device-buffer offsets), but persisted state only ever records
    # FENCED rows — work whose data-dependent readback completed — so
    # a crash can never mark never-executed rows as done (execution is
    # FIFO: consuming batch k's fence proves every batch <= k ran).
    fenced = [resume_start]

    # Serializes every state mutation/persist between the main thread
    # and the watchdog thread (concurrent writers to the same tmp file
    # could publish truncated JSON and brick every later resume).
    import threading

    state_lock = threading.RLock()

    def snapshot(final: bool = False):
      with state_lock:
        st["elapsed_s"] = base_elapsed + (time.perf_counter() - t_run0)
        persist = dict(st)
        if not final:
            persist["rows_done"] = min(st["rows_done"], fenced[0])
            # Pass accounting is appended from dispatch-side counters;
            # clamp the last entry so the persisted pass_rows never sum
            # past the fenced progress (a crash between a pass's append
            # and its final fence would otherwise skew per-pass rates).
            ps = [int(r) for r in persist.get("pass_rows", [])]
            excess = sum(ps) - persist["rows_done"]
            if excess > 0 and ps:
                ps[-1] = max(0, ps[-1] - excess)
                persist["pass_rows"] = ps
        save_state(args.state, persist)

    # Current pass-segment bookkeeping, visible to the watchdog so a
    # mid-pass restart can close the partial segment's accounting.
    cur_pass = {"start_rows": 0, "t0": 0.0}

    def _do_restart(reason: str):
        """Persist the fenced state (closing the partial pass segment
        so passes still sum to n_rows, and stamping exec_ts so the
        restart's wall stays in elapsed) and exec-restart THIS command
        in place — same pid, same argv; the fresh process resumes
        mid-pass from the state file."""
        state_lock.acquire()  # held until execv (the process dies)
        st["restarts"] = int(st.get("restarts", 0)) + 1
        seg_rows = max(0, min(st["rows_done"], fenced[0])
                       - cur_pass["start_rows"])
        if seg_rows > 0:
            st["pass_rows"].append(seg_rows)
            st["pass_s"].append(
                round(time.perf_counter() - cur_pass["t0"], 2)
            )
        st["exec_ts"] = time.time()
        snapshot()
        print(f"{reason} — exec-restarting at fenced row {fenced[0]}",
              flush=True)
        sys.stdout.flush()
        sys.stderr.flush()
        try:
            os.execv(sys.executable,
                     [sys.executable, os.path.abspath(__file__)]
                     + sys.argv[1:])
        except OSError as exc:
            # A failed execv must not strand state_lock with this
            # (possibly watchdog-thread) caller — the main thread
            # would hang at its next snapshot(). The fenced state was
            # just persisted, so a hard exit keeps the crash-safe
            # contract: rerunning the command resumes from the fence.
            print(f"exec-restart FAILED ({exc}); exiting for external "
                  "resume from the persisted state", flush=True)
            os._exit(17)

    def maybe_restart():
        """The automated leak mitigation (checked at the 30s save
        cadence in drain)."""
        if args.rss_limit_gb and args.rss_limit_gb > 0:
            r = rss_gb()
            if r > args.rss_limit_gb:
                _do_restart(
                    f"rss watchdog: {r:.1f}GB > {args.rss_limit_gb}GB "
                    "(upload-staging leak)"
                )

    # The wire can STALL outright (a fence readback that never
    # returns — observed 12+ minutes frozen); the main thread is stuck
    # inside the dead RPC then, so the stall remedy runs on its own
    # thread.
    streaming = [False]
    if args.stall_timeout_s and args.stall_timeout_s > 0:
        threading.Thread(
            target=stall_watchdog_loop,
            args=(lambda: fenced[0], lambda: streaming[0],
                  args.stall_timeout_s,
                  lambda: _do_restart(
                      f"stall watchdog: no fenced progress for "
                      f"{args.stall_timeout_s:.0f}s (wire stall)"
                  )),
            daemon=True,
        ).start()

    while st["rows_done"] < args.rows:
        pass_start_rows = st["rows_done"]
        cur_pass["start_rows"] = pass_start_rows
        offset_in_pass = st["rows_done"] % dataset_rows
        want = min(dataset_rows - offset_in_pass,
                   args.rows - st["rows_done"])

        def drain(out):
            # `out` is a DEVICE array; park it in the big on-device
            # result buffer. The lag-1 scalar fence keeps dispatch
            # honest AND bounds in-flight device buffers to ~2 reader
            # batches (block_until_ready under-blocks on this rig, so
            # a real data-dependent readback is the only backpressure
            # that works; it costs one round-trip per 1024 rows —
            # ~1-3% of the batch's 15 s of wire time).
            start = st["rows_done"]
            streaming[0] = True  # first drain: fenced progress begins;
            # arming earlier would count the resume skip-scan (minutes
            # at large offsets) as a "stall"
            nonlocal_buf[0] = _acc(nonlocal_buf[0], out, start % args.rows)
            fence, pending_fence[0] = (
                pending_fence[0],
                (jnp.sum(out), start + out.shape[0]),
            )
            if fence is not None:
                float(fence[0])
                fenced[0] = fence[1]
            st["rows_done"] = start + out.shape[0]
            now = time.perf_counter()
            if now - last_save[0] >= 30.0:
                last_save[0] = now
                snapshot()
                rate = st["rows_done"] / max(1e-9, st["elapsed_s"])
                print(f"progress: {st['rows_done']}/{args.rows} rows "
                      f"(cum {rate:.1f} rows/s, rss {rss_gb():.1f}GB)",
                      flush=True)
                maybe_restart()

        t_pass0 = time.perf_counter()
        cur_pass["t0"] = t_pass0
        stats = stream_parquet_predict(
            predictor, args.data, row_shape=ROW_SHAPE, dtype=np.uint8,
            batch_rows=4 * args.chunk, drain=drain,
            skip_rows=offset_in_pass, max_rows=want,
            device_outputs=True,
        )
        streaming[0] = False
        dt_pass = time.perf_counter() - t_pass0
        with state_lock:
            st["pass_rows"].append(st["rows_done"] - pass_start_rows)
            st["pass_s"].append(round(dt_pass, 2))
        snapshot()
        print(f"pass segment: {stats['n_rows']} rows in {dt_pass:.1f}s "
              f"({stats['n_rows']/max(dt_pass,1e-9):.1f} rows/s) "
              f"read_busy={stats['read_busy_s']}s "
              f"predict_busy={stats['predict_busy_s']}s", flush=True)

    # The ONE download: every prediction, after the stream. Included
    # in the wall via the state's elapsed accounting below.
    t_dl = time.perf_counter()
    preds = np.asarray(nonlocal_buf[0])
    dl_s = time.perf_counter() - t_dl
    st["elapsed_s"] = base_elapsed + (time.perf_counter() - t_run0)
    save_state(args.state, st)
    own = preds[resume_start % args.rows : st["rows_done"]]
    head = own[:10000] if own.size else preds[:1]
    print(f"final download: {preds.nbytes/1e6:.1f} MB of predictions "
          f"in {dl_s:.2f}s (class histogram head, this process's rows: "
          f"{np.bincount(head % 10)[:5].tolist()})", flush=True)

    wall = st["elapsed_s"]
    rate = st["rows_done"] / max(wall, 1e-9)
    wire_mb_s = rate * ROW_BYTES / 1e6
    row = {
        "config": "resnet50_inference_stream",
        "unit": "rows/sec end-to-end",
        "backend": backend,
        "n_chips": n_chips,
        "n_rows": st["rows_done"],
        "dataset_rows": dataset_rows,
        "passes": [int(r) for r in st["pass_rows"]],
        "pass_seconds": st["pass_s"],
        "pass_rates": [
            round(r / max(s, 1e-9), 1)
            for r, s in zip(st["pass_rows"], st["pass_s"])
        ],
        "wall_s": round(wall, 1),
        "rows_per_sec": round(rate, 2),
        "steady_rows_per_sec": round(rate, 2),
        "wire_MB_per_sec": round(wire_mb_s, 1),
        "chip_rate_rows_per_sec_per_chip": round(chip_rate, 1),
        "chip_busy_fraction": round(rate / (chip_rate * n_chips), 3),
        "rss_limit_gb": args.rss_limit_gb,
        "auto_restarts": int(st.get("restarts", 0)),
        "wire_dtype": "uint8 (normalize + argmax fused on device)",
        "ts": time.strftime("%Y-%m-%dT%H:%M:%S"),
    }
    print(json.dumps(row), flush=True)
    with open(args.out, "a") as f:
        f.write(json.dumps(row) + "\n")


if __name__ == "__main__":
    main()
