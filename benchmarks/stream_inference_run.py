"""BASELINE config 5 as a MEASUREMENT: columnar-ingest -> device
streaming ResNet-50 inference over >=100k real rows, end to end.

Generates a raw-uint8 Parquet dataset (224x224x3 pixels, fixed-size
binary, uncompressed — the decoded-pixel format a real ingest feeds),
then streams it disk -> reader thread -> host->device (uint8 on the
wire, normalize fused into the compiled forward) -> double-buffered
chunked forward on the real TPU chip, draining predictions as they
materialize.

Reports (one JSON line, appended to the bench JSONL):
- sustained end-to-end rows/sec over the whole run + steady-state cut
- the device-resident chip rate (same model/chunk) measured separately
- per-stage busy times and the overlap factor (>1 = pipelining won)
- a 1M-row projection from the steady-state rate, labeled by basis

Usage: python benchmarks/stream_inference_run.py [--rows 100000]
       [--data /path.parquet] [--out benchmarks/bench_r03_tpu.jsonl]
"""

from __future__ import annotations

import argparse
import json
import os
import time

import numpy as np


def ensure_dataset(path: str, rows: int, shape=(224, 224, 3)) -> int:
    from sparktorch_tpu.inference import write_rows_parquet

    if os.path.exists(path):
        import pyarrow.parquet as pq

        have = pq.ParquetFile(path).metadata.num_rows
        if have >= rows:
            print(f"dataset: {path} already has {have} rows")
            return have
        os.remove(path)
    print(f"dataset: generating {rows} uint8 rows {shape} -> {path}")
    rng = np.random.default_rng(0)
    gen_chunk = 512

    def gen():
        done = 0
        while done < rows:
            n = min(gen_chunk, rows - done)
            yield rng.integers(0, 256, (n, *shape), dtype=np.uint8)
            done += n

    t0 = time.perf_counter()
    total = write_rows_parquet(path, gen(), rows_per_group=gen_chunk)
    print(f"dataset: wrote {total} rows in {time.perf_counter() - t0:.1f}s")
    return total


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=100_000)
    ap.add_argument("--data", default="/tmp/stream_bench_100k.parquet")
    # Default next to this script, not cwd-relative: bench.py resolves
    # the ref-100k attachment at the repo's benchmarks/ path.
    ap.add_argument(
        "--out",
        default=os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "bench_r03_tpu.jsonl"),
    )
    ap.add_argument("--chunk", type=int, default=256)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from sparktorch_tpu.inference import BatchPredictor, stream_parquet_predict
    from sparktorch_tpu.models.resnet import resnet50

    backend = jax.default_backend()
    n_chips = len(jax.devices())
    print(f"backend={backend} devices={n_chips}")

    ensure_dataset(args.data, args.rows)

    module = resnet50()
    variables = module.init(
        jax.random.key(0), np.zeros((1, 224, 224, 3), np.float32)
    )
    preprocess = lambda x: x.astype(jnp.float32) / 255.0
    # Device-side argmax (the reference's predict_float semantics,
    # torch_distributed.py:112-120): the readback wire carries one
    # class id per row, not 1000 logits.
    postprocess = lambda y: jnp.argmax(y, axis=-1).astype(jnp.int32)
    predictor = BatchPredictor(
        module, variables["params"],
        {k: v for k, v in variables.items() if k != "params"},
        chunk=args.chunk, preprocess=preprocess, postprocess=postprocess,
    )
    # Compile outside the measured span.
    warm = np.zeros((args.chunk, 224, 224, 3), np.uint8)
    predictor.predict(warm)

    # Device-resident chip rate (what each chip contributes when data
    # is already in HBM — the pod-deployment per-chip ceiling).
    xd = jnp.asarray(np.tile(warm, (4, 1, 1, 1)))
    xd.block_until_ready()
    chip_rates = []
    for _ in range(3):
        t0 = time.perf_counter()
        out = predictor.predict(xd)
        chip_rates.append(xd.shape[0] / (time.perf_counter() - t0))
    chip_rate = max(chip_rates) / n_chips
    print(f"chip rate (device-resident): {chip_rate:.1f} rows/s/chip")

    # The measured end-to-end streaming run.
    marks = []  # (t, rows) cumulative, for the steady-state cut

    done_rows = [0]

    def drain(out):
        done_rows[0] += out.shape[0]
        marks.append((time.perf_counter(), done_rows[0]))

    print(f"streaming {args.rows} rows ...")
    # batch_rows = 4 chunks per reader batch: predict() then double-
    # buffers WITHIN each batch (transfer of chunk i+1 overlaps the
    # forward + readback of chunk i).
    stats = stream_parquet_predict(
        predictor, args.data, row_shape=(224, 224, 3), dtype=np.uint8,
        batch_rows=4 * args.chunk, drain=drain,
    )
    # Steady state: drop the first 10% of rows (spin-up: queue fill,
    # first transfers, allocator warm-up).
    cut = args.rows // 10
    steady = [(t, r) for t, r in marks if r >= cut]
    if len(steady) >= 2:
        (t_a, r_a), (t_b, r_b) = steady[0], steady[-1]
        steady_rate = (r_b - r_a) / max(t_b - t_a, 1e-9)
    else:
        steady_rate = stats["rows_per_sec"]

    row = {
        "config": "resnet50_inference_stream",
        "unit": "rows/sec end-to-end",
        "backend": backend,
        "n_chips": n_chips,
        **stats,
        "steady_rows_per_sec": round(steady_rate, 2),
        "chip_rate_rows_per_sec_per_chip": round(chip_rate, 1),
        "projected_1M_rows_s_host_stream": round(1_000_000 / steady_rate, 1),
        "projected_1M_rows_s_chip_rate": round(
            1_000_000 / (chip_rate * n_chips), 1
        ),
        "wire_dtype": "uint8 (normalize fused on device)",
        "ts": time.strftime("%Y-%m-%dT%H:%M:%S"),
    }
    print(json.dumps(row))
    with open(args.out, "a") as f:
        f.write(json.dumps(row) + "\n")


if __name__ == "__main__":
    main()
