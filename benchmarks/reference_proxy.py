"""Reference-architecture proxy measurement.

The reference (sparktorch) trains torch models on Spark executors —
CPU in its own tests/CI (environment.yml pins CPU pytorch; examples
run local[*]). This measures the same MNIST-CNN workload (batch 1024,
forward+backward+step) in torch on this machine's CPU to anchor
bench.py's vs_baseline ratio.
"""
import json, time
import torch
import torch.nn as nn

class CNN(nn.Module):
    def __init__(self):
        super().__init__()
        self.c1 = nn.Conv2d(1, 32, 3, padding=1)
        self.c2 = nn.Conv2d(32, 64, 3, padding=1)
        self.f1 = nn.Linear(64*7*7, 128)
        self.f2 = nn.Linear(128, 10)
    def forward(self, x):
        x = x.view(-1, 1, 28, 28)
        x = torch.relu(self.c1(x)); x = torch.max_pool2d(x, 2)
        x = torch.relu(self.c2(x)); x = torch.max_pool2d(x, 2)
        x = x.flatten(1)
        x = torch.relu(self.f1(x))
        return self.f2(x)

def main():
    torch.manual_seed(0)
    model = CNN()
    opt = torch.optim.Adam(model.parameters(), lr=1e-3)
    crit = nn.CrossEntropyLoss()
    x = torch.randn(1024, 784)
    y = torch.randint(0, 10, (1024,))
    for _ in range(3):  # warmup
        opt.zero_grad(); loss = crit(model(x), y); loss.backward(); opt.step()
    iters = 10
    t0 = time.perf_counter()
    for _ in range(iters):
        opt.zero_grad(); loss = crit(model(x), y); loss.backward(); opt.step()
    dt = time.perf_counter() - t0
    print(json.dumps({"reference_proxy_examples_per_sec": round(1024*iters/dt, 1)}))

if __name__ == "__main__":
    main()
