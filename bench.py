"""Benchmark: MNIST-CNN synchronous training throughput on real TPU.

North-star metric from BASELINE.json: examples/sec/chip (MNIST-CNN).
The reference publishes no numbers (BASELINE.md), so ``vs_baseline``
compares against a measured reference-architecture proxy: the same
workload run through torch (CPU, the reference's test substrate) would
be orders slower; we report vs_baseline as the ratio to a fixed
reference throughput recorded in REFERENCE_BASELINE below once
measured, else 1.0.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import time

import numpy as np

# Measured reference proxy (examples/sec) for the same MNIST-CNN
# workload: torch-CPU forward+backward+Adam step, batch 1024, on this
# machine — the substrate the reference's own tests/CI train on
# (environment.yml pins CPU pytorch). Measured 2026-07-29 by
# benchmarks/reference_proxy.py.
REFERENCE_BASELINE_EXAMPLES_PER_SEC = 1120.8

BATCH = 1024
ITERS = 30
WARMUP = 5


def main() -> None:
    import jax

    from sparktorch_tpu.models import MnistCNN
    from sparktorch_tpu.parallel.mesh import MeshConfig, build_mesh, replicated
    from sparktorch_tpu.train.step import create_train_state, make_train_epoch
    from sparktorch_tpu.train.sync import prepare_sharded_batch
    from sparktorch_tpu.utils.data import handle_features
    from sparktorch_tpu.utils.serde import ModelSpec

    devices = jax.devices()
    n_chips = len(devices)
    mesh = build_mesh(MeshConfig(), devices)

    spec = ModelSpec(module=MnistCNN(), loss="cross_entropy",
                     optimizer="adam", optimizer_params={"lr": 1e-3},
                     input_shape=(784,))
    rng = np.random.default_rng(0)
    x = rng.normal(0, 1, (BATCH, 784)).astype(np.float32)
    y = rng.integers(0, 10, (BATCH,)).astype(np.int32)
    batch, _ = handle_features(x, y)
    batch = prepare_sharded_batch(batch, mesh)

    tx = spec.make_optimizer()
    with mesh:
        state = create_train_state(spec, jax.random.key(0),
                                   sample_x=batch.x[:1], tx=tx)
    state = jax.device_put(state, replicated(mesh))
    # The whole measured run is ONE compiled call: ITERS steps fused by
    # lax.scan — zero per-step Python/dispatch (the framework's fast
    # path; the reference pays Python + per-param gloo per step).
    epoch = make_train_epoch(spec.make_module().apply, spec.loss_fn(), tx,
                             mesh, steps_per_call=ITERS)

    import jax.numpy as jnp

    for _ in range(WARMUP):
        state, metrics = epoch(state, batch)
    # float() forces full materialization — on the tunneled axon
    # platform block_until_ready alone under-blocks.
    float(jnp.sum(metrics.loss))

    t0 = time.perf_counter()
    state, metrics = epoch(state, batch)
    float(jnp.sum(metrics.loss))
    dt = time.perf_counter() - t0

    examples_per_sec = BATCH * ITERS / dt
    per_chip = examples_per_sec / n_chips
    vs_baseline = (
        per_chip / REFERENCE_BASELINE_EXAMPLES_PER_SEC
        if REFERENCE_BASELINE_EXAMPLES_PER_SEC
        else 1.0
    )
    print(json.dumps({
        "metric": "examples/sec/chip (MNIST-CNN sync DP, batch 1024)",
        "value": round(per_chip, 1),
        "unit": "examples/sec/chip",
        "vs_baseline": round(vs_baseline, 3),
    }))


if __name__ == "__main__":
    main()
