"""Driver entry: prints ONE JSON line for the headline benchmark.

The full five-config BASELINE.md suite lives in
:mod:`sparktorch_tpu.bench` (``sparktorch-tpu-bench --config all``);
raw logs are kept under ``benchmarks/`` per the BASELINE.md protocol.
"""

from sparktorch_tpu.bench import main

if __name__ == "__main__":
    main(["--config", "headline"])
